"""The typed OPENSIM_* env-knob registry (ISSUE 12 satellite): every knob
registered, validators accept their documented defaults, docs/env.md stays
generated, and lint rule OSL1401 sweeps raw reads."""

import os

import pytest

from opensim_tpu.utils import envknobs


def test_every_knob_is_prefixed_and_documented():
    assert envknobs.KNOBS, "registry must not be empty"
    for name, knob in envknobs.KNOBS.items():
        assert name == knob.name
        assert name.startswith("OPENSIM_")
        assert knob.doc.strip(), f"{name} has no doc line"
        assert knob.type in ("int", "float", "flag", "enum", "str", "path", "spec")
        assert knob.on_error in ("default", "raise")


def test_validators_accept_their_documented_defaults():
    """The documented default must parse through the registered validator —
    the drift this registry exists to prevent."""
    for knob in envknobs.KNOBS.values():
        if knob.validator is None or knob.default == "":
            continue
        knob.validator(knob.default)  # must not raise


def test_raw_fails_loudly_on_unregistered_name():
    with pytest.raises(KeyError, match="not registered"):
        envknobs.raw("OPENSIM_NO_SUCH_KNOB")
    with pytest.raises(KeyError, match="not registered"):
        envknobs.is_set("OPENSIM_NO_SUCH_KNOB")


def test_raw_passthrough_and_default(monkeypatch):
    monkeypatch.delenv("OPENSIM_CAPACITY_TOPK", raising=False)
    assert envknobs.raw("OPENSIM_CAPACITY_TOPK") == ""
    assert envknobs.raw("OPENSIM_CAPACITY_TOPK", "10") == "10"
    monkeypatch.setenv("OPENSIM_CAPACITY_TOPK", "7")
    assert envknobs.raw("OPENSIM_CAPACITY_TOPK") == "7"
    assert envknobs.is_set("OPENSIM_CAPACITY_TOPK")


def test_value_parses_and_degrades_per_contract(monkeypatch):
    # "default" knobs warn and fall back on garbage
    monkeypatch.setenv("OPENSIM_FLIGHT_RECORDER_N", "not-a-number")
    assert envknobs.value("OPENSIM_FLIGHT_RECORDER_N") == 64
    monkeypatch.setenv("OPENSIM_FLIGHT_RECORDER_N", "9")
    assert envknobs.value("OPENSIM_FLIGHT_RECORDER_N") == 9
    # "raise" knobs surface the operator typo
    monkeypatch.setenv("OPENSIM_SCAN_UNROLL", "zero")
    with pytest.raises(ValueError):
        envknobs.value("OPENSIM_SCAN_UNROLL")


def test_docs_env_md_is_generated_and_in_sync():
    """docs/env.md is generated from the registry (make docs); a knob added
    without regenerating the docs fails here."""
    path = os.path.join(os.path.dirname(__file__), "..", "docs", "env.md")
    with open(path) as f:
        on_disk = f.read()
    assert on_disk == envknobs.render_markdown(), (
        "docs/env.md is stale; regenerate with `make docs`"
    )


def test_osl1401_flags_raw_reads_and_stays_quiet_on_registry_use():
    from opensim_tpu.analysis import lint_source

    bad = (
        "import os\n"
        'a = os.environ.get("OPENSIM_TRACE", "1")\n'
        'b = os.environ["OPENSIM_FAULTS"]\n'
        'c = os.getenv("OPENSIM_NATIVE")\n'
        'd = "OPENSIM_JIT_CACHE" in os.environ\n'
    )
    findings = lint_source(bad, path="opensim_tpu/somewhere.py", rules=["env-registry"])
    assert len(findings) == 4
    assert all(f.code == "OSL1401" for f in findings)

    good = (
        "import os\n"
        "from opensim_tpu.utils import envknobs\n"
        'a = envknobs.raw("OPENSIM_TRACE", "1")\n'
        # writes are legal: the CLI arms knobs for downstream code
        'os.environ["OPENSIM_NATIVE"] = "1"\n'
        # non-OPENSIM reads are out of scope
        'j = os.environ.get("JAX_PLATFORMS", "cpu")\n'
    )
    assert lint_source(good, path="opensim_tpu/somewhere.py", rules=["env-registry"]) == []
    # the registry module itself is the sanctioned read path
    assert (
        lint_source(bad, path="opensim_tpu/utils/envknobs.py", rules=["env-registry"])
        == []
    )


def test_call_site_literal_defaults_match_the_registry():
    """``envknobs.raw(NAME, default)`` callers keep site-local defaults for
    unset-vs-empty semantics; this sweep gates them against the registered
    default so docs/env.md can never document one value while a call site
    runs another (the drift the registry exists to prevent)."""
    import re

    pkg = os.path.join(os.path.dirname(__file__), "..", "opensim_tpu")
    pattern = re.compile(r'envknobs\.raw\(\s*"(OPENSIM_\w+)"\s*,\s*"([^"]*)"\s*\)')
    checked = 0
    for root, _dirs, files in os.walk(pkg):
        for fname in files:
            if not fname.endswith(".py") or fname == "envknobs.py":
                continue
            src = open(os.path.join(root, fname)).read()
            for name, literal in pattern.findall(src):
                assert name in envknobs.KNOBS, f"{fname}: unregistered {name}"
                assert literal == envknobs.KNOBS[name].default, (
                    f"{fname}: raw({name!r}, {literal!r}) disagrees with the "
                    f"registered default {envknobs.KNOBS[name].default!r}"
                )
                checked += 1
    assert checked >= 5  # the sweep found real call sites


def test_osl1401_suppression():
    from opensim_tpu.analysis import lint_source

    src = (
        "import os\n"
        'a = os.environ.get("OPENSIM_TRACE")  # opensim-lint: disable=env-registry\n'
    )
    assert lint_source(src, path="opensim_tpu/x.py", rules=["env-registry"]) == []
