"""Parallel per-file lint tier + ``--changed``: the process-pool path
must be byte-identical to serial (including parse errors), cache its
results, degrade to serial when the pool cannot pay for itself, and the
diff-scoped flow must pick the right files out of ``git status``.
Also pins the OSL18xx cache axis: a policy-VALUE-only edit to
``encoding/dtypes.py`` must invalidate the cached project pass."""

import os
import subprocess
import textwrap

from opensim_tpu.analysis import lint_paths
from opensim_tpu.analysis.__main__ import _git_changed_files
from opensim_tpu.analysis.core import _PARALLEL_MIN_MISSES, _resolve_jobs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
UTILS = os.path.join(REPO, "opensim_tpu", "utils")


def _write_tree(root, files):
    for rel, src in files.items():
        path = os.path.join(root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as fh:
            fh.write(textwrap.dedent(src))


# -- process-pool tier ------------------------------------------------------


def test_resolve_jobs_degrades_to_serial():
    assert _resolve_jobs(1, 100) == 1
    assert _resolve_jobs(4, _PARALLEL_MIN_MISSES - 1) == 1  # pool can't pay
    assert _resolve_jobs(4, 100) == 4
    assert _resolve_jobs(16, 10) == 10  # never more workers than misses
    assert _resolve_jobs(None, 0) == 1  # warm cache: nothing to fan out


def test_parallel_is_byte_identical_to_serial(tmp_path):
    stats_s, stats_p = {}, {}
    serial = lint_paths([UTILS], stats=stats_s,
                        cache_path=str(tmp_path / "s.json"), jobs=1)
    par = lint_paths([UTILS], stats=stats_p,
                     cache_path=str(tmp_path / "p.json"), jobs=2)
    assert stats_s["jobs"] == 1
    assert stats_p["jobs"] == 2, "pool did not engage on a cold run"
    assert [f.as_dict() for f in serial] == [f.as_dict() for f in par]


def test_parallel_results_are_cached(tmp_path):
    cache = str(tmp_path / "cache.json")
    cold = lint_paths([UTILS], cache_path=cache, jobs=2)
    stats: dict = {}
    warm = lint_paths([UTILS], stats=stats, cache_path=cache, jobs=2)
    assert stats["cache_misses"] == 0 and stats["cache_hits"] > 0
    assert stats["jobs"] == 1  # no misses -> nothing to fan out
    assert [f.as_dict() for f in warm] == [f.as_dict() for f in cold]


def test_parallel_parse_errors_match_serial(tmp_path):
    tree = str(tmp_path / "proj")
    files = {f"m{i}.py": "x = 1\n" for i in range(_PARALLEL_MIN_MISSES)}
    files["broken.py"] = "def oops(:\n"
    _write_tree(tree, files)
    serial = lint_paths([tree], cache_path=str(tmp_path / "s.json"), jobs=1)
    par = lint_paths([tree], cache_path=str(tmp_path / "p.json"), jobs=2)
    assert [f.as_dict() for f in serial] == [f.as_dict() for f in par]
    assert any(f.code == "OSL000" for f in par), "parse error lost in the pool"


# -- --changed file selection ----------------------------------------------


def _git(cwd, *args):
    subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=cwd, check=True, capture_output=True,
    )


def test_git_changed_files_scopes_and_maps_cc_to_native(tmp_path, monkeypatch):
    repo = str(tmp_path / "repo")
    _write_tree(repo, {
        "pkg/a.py": "a = 1\n",
        "pkg/b.py": "b = 1\n",
        "pkg/native/__init__.py": "x = 1\n",
        "pkg/native/engine.cc": "// v1\n",
        "elsewhere/c.py": "c = 1\n",
    })
    _git(repo, "init", "-q")
    _git(repo, "add", ".")
    _git(repo, "commit", "-qm", "seed")
    # modify one .py, one .cc, one out-of-scope file; add one untracked .py
    _write_tree(repo, {
        "pkg/a.py": "a = 2\n",
        "pkg/native/engine.cc": "// v2\n",
        "elsewhere/c.py": "c = 2\n",
        "pkg/new.py": "n = 1\n",
    })
    monkeypatch.chdir(repo)
    changed = _git_changed_files(["pkg"])
    # a.py (modified), new.py (untracked), and the native package pulled
    # in by its .cc edit; b.py (clean) and elsewhere/ (out of scope) not
    assert changed == ["pkg/a.py", "pkg/native/__init__.py", "pkg/new.py"]


def test_git_changed_files_outside_checkout_returns_none(tmp_path, monkeypatch):
    monkeypatch.chdir(str(tmp_path))
    monkeypatch.setenv("GIT_DIR", str(tmp_path / "nope"))
    assert _git_changed_files(["pkg"]) is None


def test_changed_style_scoped_run_keeps_full_project_slot(tmp_path):
    # the point of the 4-slot project cache: a diff-scoped run (different
    # path set -> different project digest) lands in its own slot, and the
    # next full run still reuses the full-repo slot
    tree = str(tmp_path / "proj")
    cache = str(tmp_path / "cache.json")
    _write_tree(tree, {"a/x.py": "x = 1\n", "b/y.py": "y = 2\n"})
    lint_paths([tree], cache_path=cache)
    lint_paths([os.path.join(tree, "a", "x.py")], cache_path=cache)  # scoped
    stats: dict = {}
    lint_paths([tree], stats=stats, cache_path=cache)
    assert stats["project_pass"] == "reused", "scoped run evicted the full slot"
    assert stats["cache_misses"] == 0


# -- OSL18xx cache invalidation on policy-value edits -----------------------

_MINI_DTYPES = """
import numpy as np

FLOAT_DTYPE = np.float32
INT_DTYPE = np.int32

AXIS_ALIASES = {}
ARENA_CONTRACTS = {"alloc": ("FLOAT_DTYPE", ("N", "R"))}
STATE_CONTRACTS = {}
BUFFER_FIELD_ALIASES = {}
KERNEL_ARG_CONTRACTS = {}
STRUCT_PARAM_NAMES = {}
"""

_MINI_BUILDER = """
import numpy as np

def build(n, r):
    from .state import EncodedCluster
    return EncodedCluster(alloc=np.zeros((n, r)))
"""


def test_policy_value_edit_invalidates_cached_findings(tmp_path):
    tree = str(tmp_path / "proj")
    cache = str(tmp_path / "cache.json")
    _write_tree(tree, {
        "encoding/dtypes.py": _MINI_DTYPES,
        "encoding/builder.py": _MINI_BUILDER,
    })
    rules = ["array-off-policy"]
    cold = lint_paths([tree], rules=rules, cache_path=cache)
    assert [f.code for f in cold] == ["OSL1801"]  # f64 default vs f32 policy
    # warm: same answer from the project slot
    stats: dict = {}
    warm = lint_paths([tree], rules=rules, stats=stats, cache_path=cache)
    assert stats["project_pass"] == "reused"
    assert [f.as_dict() for f in warm] == [f.as_dict() for f in cold]
    # flip ONLY the policy VALUE: the same builder is now on-policy, and
    # the warm cache must notice (dtypes.py content feeds the digest)
    with open(os.path.join(tree, "encoding", "dtypes.py"), "w") as fh:
        fh.write(_MINI_DTYPES.replace("np.float32", "np.float64"))
    after = lint_paths([tree], rules=rules, cache_path=cache)
    assert after == [], "stale project slot survived a policy-value edit"
