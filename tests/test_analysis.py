"""opensim-lint (opensim_tpu/analysis): each rule fires on a known-bad
fixture, stays silent on the known-good twin, and honors the suppression
syntax — plus the meta-test that the repo itself is lint-clean and the
typed-core signature gate holds."""

import os
import textwrap

from opensim_tpu.analysis import RULES, lint_paths, lint_source, render_human, render_json
from opensim_tpu.analysis.typed_core import check_typed_core

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _codes(src, path="x.py", rules=None):
    return [f.code for f in lint_source(textwrap.dedent(src), path=path, rules=rules)]


# ---------------------------------------------------------------------------
# OSL101 jit-boundary
# ---------------------------------------------------------------------------

JIT_PATH = "opensim_tpu/engine/fixture.py"  # rule is scoped to engine/ops/parallel


def test_jit_boundary_fires_on_host_calls_in_traced_code():
    src = """
    import time, random, jax
    import jax.numpy as jnp
    import numpy as np

    @jax.jit
    def step(x):
        t = time.monotonic()          # host clock at trace time
        y = np.asarray(x)             # tracer -> host numpy
        v = x.sum().item()            # device sync
        if jnp.any(x > 0):            # python control flow on tracer
            x = x + 1
        return x
    """
    codes = _codes(src, path=JIT_PATH, rules=["jit-boundary"])
    assert codes == ["OSL101"] * 4


def test_jit_boundary_reaches_through_call_graph_and_lax_entry_points():
    src = """
    import random, jax

    def helper(c):
        return c * random.random()

    def body(carry, x):
        return helper(carry), x

    def outer(xs):
        return jax.lax.scan(body, 0, xs)
    """
    codes = _codes(src, path=JIT_PATH, rules=["jit-boundary"])
    assert codes == ["OSL101"]  # random.random inside helper, via body


def test_jit_boundary_silent_on_host_side_code_and_other_dirs():
    src = """
    import time, jax

    @jax.jit
    def step(x):
        return x + 1

    def host_driver(xs):
        t0 = time.monotonic()        # fine: not traced
        return step(xs), time.monotonic() - t0
    """
    assert _codes(src, path=JIT_PATH, rules=["jit-boundary"]) == []
    bad = """
    import time, jax

    @jax.jit
    def step(x):
        return time.time()
    """
    # same code outside engine/ops/parallel is out of the rule's scope
    assert _codes(bad, path="opensim_tpu/chart/fixture.py", rules=["jit-boundary"]) == []


def test_jit_boundary_suppression():
    src = """
    import time, jax

    @jax.jit
    def step(x):
        t = time.monotonic()  # opensim-lint: disable=jit-boundary
        return x
    """
    assert _codes(src, path=JIT_PATH, rules=["jit-boundary"]) == []


# ---------------------------------------------------------------------------
# OSL201 dtype-drift
# ---------------------------------------------------------------------------

ENC_PATH = "opensim_tpu/encoding/fixture.py"  # rule is scoped to encoding/


def test_dtype_drift_fires_on_float64_and_default_dtype():
    src = """
    import numpy as np

    def build(n):
        a = np.zeros((n,))                       # default dtype
        b = np.arange(n + 1, dtype=np.float64)   # bare float64
        c = np.full((n,), -1)                    # no dtype
        return a, b, c
    """
    codes = _codes(src, path=ENC_PATH, rules=["dtype-drift"])
    assert codes == ["OSL201"] * 3


def test_dtype_drift_silent_on_policy_compliant_arrays():
    src = """
    import numpy as np
    from opensim_tpu.encoding.dtypes import FLOAT_DTYPE, INT_DTYPE, log_size_table

    def build(n, a):
        x = np.zeros((n,), dtype=FLOAT_DTYPE)
        y = np.full((n,), -1, np.int32)          # positional dtype
        z = np.full(a.shape, 0, dtype=a.dtype)   # dtype-preserving growth
        return x, y, z, log_size_table(n)
    """
    assert _codes(src, path=ENC_PATH, rules=["dtype-drift"]) == []
    # out of scope: non-encoding paths may use numpy defaults
    bad = "import numpy as np\na = np.zeros((3,))\n"
    assert _codes(bad, path="opensim_tpu/planner/fixture.py", rules=["dtype-drift"]) == []


def test_dtype_drift_file_level_suppression():
    src = """
    # opensim-lint: disable-file=dtype-drift
    import numpy as np
    a = np.zeros((4,))
    """
    assert _codes(src, path=ENC_PATH, rules=["dtype-drift"]) == []


# ---------------------------------------------------------------------------
# OSL301 determinism
# ---------------------------------------------------------------------------


def test_determinism_fires_on_set_iteration_and_hash_fed_dict_views():
    src = """
    import hashlib

    def fingerprint(d):
        h = hashlib.blake2b()
        for k, v in d.items():        # dict order feeds the hash
            h.update(str((k, v)).encode())
        return h.hexdigest()

    def render(names):
        return ",".join({n for n in names})   # set order into a stream
    """
    codes = _codes(src, rules=["determinism"])
    assert codes == ["OSL301"] * 2


def test_determinism_silent_on_sorted_iteration():
    src = """
    import hashlib

    def fingerprint(d):
        h = hashlib.blake2b()
        for k in sorted(d.items()):
            h.update(str(k).encode())
        return h.hexdigest()

    def render(names):
        return ",".join(sorted(set(names)))

    def count(names):
        return len(set(names))        # cardinality: order irrelevant

    def plain(d):
        return [v for v in d.values()]  # dict order, no hash scope: fine
    """
    assert _codes(src, rules=["determinism"]) == []


def test_determinism_suppression_on_previous_line():
    src = """
    def render(names):
        # opensim-lint: disable=determinism
        return ",".join({n for n in names})
    """
    assert _codes(src, rules=["determinism"]) == []


# ---------------------------------------------------------------------------
# OSL401 cache-mutation
# ---------------------------------------------------------------------------


def test_cache_mutation_fires_on_mutation_after_fingerprint():
    src = """
    from opensim_tpu.engine.prepcache import fingerprint_cluster

    def bad(cluster, extra_pod):
        fp = fingerprint_cluster(cluster)
        cluster.pods.append(extra_pod)          # direct container mutation
        for p in cluster.pods:
            p.phase = "Running"                 # via a loop alias
        return fp
    """
    codes = _codes(src, rules=["cache-mutation"])
    assert codes == ["OSL401"] * 2


def test_cache_mutation_silent_when_invalidated_or_before_fingerprint():
    src = """
    from opensim_tpu.engine.prepcache import fingerprint_cluster

    def fixed(cluster, cache, extra_pod):
        fp = fingerprint_cluster(cluster)
        cluster.pods.append(extra_pod)
        cache.invalidate(cluster)               # the sanctioned escape

    def mutate_then_fingerprint(cluster, extra_pod):
        cluster.pods.append(extra_pod)          # before: content not yet keyed
        return fingerprint_cluster(cluster)

    def unrelated(cluster, other, extra_pod):
        fp = fingerprint_cluster(cluster)
        other.pods.append(extra_pod)            # different object
    """
    assert _codes(src, rules=["cache-mutation"]) == []


def test_cache_mutation_suppression():
    src = """
    from opensim_tpu.engine.prepcache import fingerprint_cluster

    def bad(cluster, extra_pod):
        fp = fingerprint_cluster(cluster)
        cluster.pods.append(extra_pod)  # opensim-lint: disable=cache-mutation
    """
    assert _codes(src, rules=["cache-mutation"]) == []


# ---------------------------------------------------------------------------
# OSL501 exception-swallow
# ---------------------------------------------------------------------------


def test_exception_swallow_fires_on_silent_broad_handlers():
    src = """
    def swallow():
        try:
            risky()
        except Exception:
            pass

    def swallow_bare():
        try:
            risky()
        except:
            return None
    """
    codes = _codes(src, rules=["exception-swallow"])
    assert codes == ["OSL501"] * 2


def test_exception_swallow_silent_on_raise_log_or_narrow():
    src = """
    import logging
    log = logging.getLogger(__name__)

    def translated():
        try:
            risky()
        except Exception as e:
            raise RuntimeError(str(e)) from e

    def logged():
        try:
            risky()
        except Exception as e:
            log.warning("risky failed: %s", e)

    def narrowed():
        try:
            risky()
        except ValueError:
            pass
    """
    assert _codes(src, rules=["exception-swallow"]) == []


def test_exception_swallow_suppression_by_code():
    src = """
    def swallow():
        try:
            risky()
        except Exception:  # opensim-lint: disable=OSL501
            pass
    """
    assert _codes(src, rules=["exception-swallow"]) == []


# ---------------------------------------------------------------------------
# engine plumbing + meta-tests
# ---------------------------------------------------------------------------


def test_unknown_rule_is_an_error():
    import pytest

    with pytest.raises(KeyError):
        lint_source("x = 1", rules=["no-such-rule"])


def test_render_formats():
    findings = lint_source(
        "def f():\n    try:\n        g()\n    except Exception:\n        pass\n",
        path="a.py",
    )
    assert len(findings) == 1
    human = render_human(findings)
    assert "a.py:4" in human and "OSL501" in human
    import json

    data = json.loads(render_json(findings))
    assert data[0]["rule"] == "exception-swallow" and data[0]["line"] == 4


def test_all_five_rules_registered():
    assert {
        "jit-boundary",
        "dtype-drift",
        "determinism",
        "cache-mutation",
        "exception-swallow",
    } <= set(RULES)


def test_repo_is_lint_clean():
    """The acceptance gate: `make lint` exits 0 on the package."""
    findings = lint_paths([os.path.join(REPO, "opensim_tpu")])
    assert findings == [], render_human(findings)


def test_strict_core_has_no_suppressions():
    """engine/prepcache.py and encoding/state.py must be clean WITHOUT
    suppression comments (ISSUE acceptance)."""
    for rel in ("opensim_tpu/engine/prepcache.py", "opensim_tpu/encoding/state.py"):
        with open(os.path.join(REPO, rel)) as fh:
            assert "opensim-lint: disable" not in fh.read(), rel


def test_typed_core_signatures_complete():
    assert check_typed_core(REPO) == []


def test_cli_main():
    from opensim_tpu.analysis.__main__ import main

    assert main(["--list-rules"]) == 0
    assert main([os.path.join(REPO, "opensim_tpu", "analysis")]) == 0


def test_pyproject_defaults_are_read():
    from opensim_tpu.analysis.__main__ import pyproject_defaults

    cfg = pyproject_defaults(os.path.join(REPO, "pyproject.toml"))
    assert cfg.get("paths") == ["opensim_tpu"]
    assert "jit-boundary" in cfg.get("rules", [])


def test_pyproject_rules_list_covers_every_registered_rule():
    # the [tool.opensim-lint] rules array is the default selection for
    # `make lint`: a registered rule missing from it silently never runs
    from opensim_tpu.analysis import RULES
    from opensim_tpu.analysis.__main__ import pyproject_defaults

    cfg = pyproject_defaults(os.path.join(REPO, "pyproject.toml"))
    assert sorted(cfg.get("rules", [])) == sorted(RULES)


def test_cache_mutation_release_is_per_object():
    # review fix: invalidate(cluster) must NOT silence the apps mutation
    src = """
    from opensim_tpu.engine.prepcache import fingerprint_cluster, fingerprint_apps

    def partial_release(cluster, apps, cache, extra):
        fingerprint_cluster(cluster)
        fingerprint_apps(apps)
        cluster.pods.append(extra)
        apps.pods.append(extra)
        cache.invalidate(cluster)      # covers cluster only
    """
    findings = lint_source(textwrap.dedent(src), rules=["cache-mutation"])
    assert len(findings) == 1 and "apps" in findings[0].message


def test_cache_mutation_argless_invalidate_releases_all():
    src = """
    from opensim_tpu.engine.prepcache import fingerprint_cluster, fingerprint_apps

    def full_release(cluster, apps, cache, extra):
        fingerprint_cluster(cluster)
        fingerprint_apps(apps)
        cluster.pods.append(extra)
        apps.pods.append(extra)
        cache.invalidate()             # drops everything
    """
    assert _codes(src, rules=["cache-mutation"]) == []


def test_cache_mutation_touch_on_loop_alias_releases_its_root():
    src = """
    from opensim_tpu.engine.prepcache import fingerprint_cluster

    def touched(cluster):
        fingerprint_cluster(cluster)
        for p in cluster.pods:
            p.phase = "Running"
            p.touch()                  # alias of cluster: releases it
    """
    assert _codes(src, rules=["cache-mutation"]) == []


def test_cache_mutation_nested_function_reports_once():
    src = """
    from opensim_tpu.engine.prepcache import fingerprint_cluster

    def outer():
        def inner(cluster, extra):
            fingerprint_cluster(cluster)
            cluster.pods.append(extra)
        return inner
    """
    assert _codes(src, rules=["cache-mutation"]) == ["OSL401"]


def test_typed_core_catches_multiline_signature_ignore(tmp_path):
    from opensim_tpu.analysis import typed_core

    bad = tmp_path / "mod.py"
    bad.write_text(
        "def f(\n    x: int,\n) -> int:  # type: ignore[override]\n    return x\n"
    )
    orig = typed_core.STRICT_MODULES
    typed_core.STRICT_MODULES = ("mod.py",)
    try:
        problems = typed_core.check_typed_core(str(tmp_path))
    finally:
        typed_core.STRICT_MODULES = orig
    assert len(problems) == 1 and "type: ignore" in problems[0]


def test_determinism_flags_sum_over_float_set():
    src = """
    def total(xs):
        return sum({float(x) for x in xs})   # order-dependent in the last ulp
    """
    assert _codes(src, rules=["determinism"]) == ["OSL301"]


def test_typed_core_catches_one_line_def_ignore(tmp_path):
    from opensim_tpu.analysis import typed_core

    bad = tmp_path / "mod.py"
    bad.write_text("def f(x: int) -> int: return x  # type: ignore\n")
    orig = typed_core.STRICT_MODULES
    typed_core.STRICT_MODULES = ("mod.py",)
    try:
        problems = typed_core.check_typed_core(str(tmp_path))
    finally:
        typed_core.STRICT_MODULES = orig
    assert len(problems) == 1 and "type: ignore" in problems[0]


# ---------------------------------------------------------------------------
# OSL601 unbounded-retry
# ---------------------------------------------------------------------------


def test_unbounded_retry_flags_while_true_around_network_call():
    src = """
    import urllib.request

    def fetch(url):
        while True:
            try:
                return urllib.request.urlopen(url)
            except OSError:
                pass                      # swallow and hammer forever
    """
    assert _codes(src, rules=["unbounded-retry"]) == ["OSL601"]


def test_unbounded_retry_flags_constant_sleep_in_loop():
    src = """
    import time

    def poll(client):
        for _ in range(10):
            if client.ready():
                break
            time.sleep(5)                # constant interval: no backoff
    """
    assert _codes(src, rules=["unbounded-retry"]) == ["OSL601"]


def test_unbounded_retry_accepts_bounded_backoff_and_escaping_handlers():
    src = """
    import time
    import urllib.request

    def fetch(url, attempts=3):
        for k in range(attempts):
            try:
                return urllib.request.urlopen(url)
            except OSError:
                if k == attempts - 1:
                    raise
                time.sleep(0.1 * 2 ** k)   # computed: exponential backoff

    def fail_fast(url):
        while True:
            try:
                return urllib.request.urlopen(url)
            except OSError:
                raise RuntimeError("down")  # handler escapes: not a retry loop

    def prompt_loop(ask):
        while True:                          # no network/device call: fine
            try:
                return int(ask())
            except ValueError:
                pass
    """
    assert _codes(src, rules=["unbounded-retry"]) == []


def test_unbounded_retry_suppression_and_device_calls():
    src = """
    import time, jax

    def hammer(x):
        while True:
            try:
                jax.device_put(x)  # opensim-lint: disable=unbounded-retry
            except RuntimeError:
                continue
    """
    # the loop finding anchors on the `while` line, which has no suppression
    flagged = _codes(src, rules=["unbounded-retry"])
    assert flagged == ["OSL601"]
    src2 = """
    import jax

    def hammer(x):
        # opensim-lint: disable=unbounded-retry
        while True:
            try:
                jax.device_put(x)
            except RuntimeError:
                continue
    """
    assert _codes(src2, rules=["unbounded-retry"]) == []


def test_unbounded_retry_nested_loops_report_sleep_once():
    src = """
    import time

    def poll():
        while running():
            for _ in range(3):
                time.sleep(2)
    """
    # the sleep belongs to its NEAREST enclosing loop only: one finding,
    # not one per enclosing loop level
    assert _codes(src, rules=["unbounded-retry"]) == ["OSL601"]


# ---------------------------------------------------------------------------
# OSL701 deadline-span
# ---------------------------------------------------------------------------


def test_deadline_span_fires_on_uninstrumented_phase_boundary():
    src = """
    from opensim_tpu.resilience.deadline import check_deadline

    def prepare_things(cluster):
        check_deadline("prepare")
        return encode(cluster)
    """
    assert _codes(src, path="opensim_tpu/engine/fixture.py", rules=["deadline-span"]) == ["OSL701"]


def test_deadline_span_fires_on_bare_deadline_scope():
    src = """
    from opensim_tpu.resilience.deadline import deadline_scope

    def handle(req, deadline):
        with deadline_scope(deadline):
            return run(req)
    """
    assert _codes(src, path="opensim_tpu/server/fixture.py", rules=["deadline-span"]) == ["OSL701"]


def test_deadline_span_silent_when_span_present():
    src = """
    from opensim_tpu.obs import trace as obs
    from opensim_tpu.resilience.deadline import check_deadline

    def prepare_things(cluster):
        check_deadline("prepare")
        with obs.span("prepare"):
            return encode(cluster)

    def measured(cluster):
        check_deadline("encode")
        t0 = now()
        out = encode(cluster)
        obs.record_span("encode", now() - t0)
        return out
    """
    assert _codes(src, path="opensim_tpu/engine/fixture.py", rules=["deadline-span"]) == []


def test_deadline_span_nested_def_does_not_credit_outer():
    src = """
    from opensim_tpu.obs import trace as obs
    from opensim_tpu.resilience.deadline import check_deadline

    def outer(cluster):
        check_deadline("snapshot")

        def callback():
            with obs.span("snapshot"):
                pass

        return fetch(cluster, callback)
    """
    # the span lives in the nested function, not at the boundary itself
    assert _codes(src, path="opensim_tpu/engine/fixture.py", rules=["deadline-span"]) == ["OSL701"]


def test_deadline_span_suppression_and_exempt_paths():
    src = """
    from opensim_tpu.resilience.deadline import check_deadline

    def quick(cluster):
        check_deadline("decode")  # opensim-lint: disable=deadline-span
        return decode(cluster)
    """
    assert _codes(src, path="opensim_tpu/engine/fixture.py", rules=["deadline-span"]) == []
    # the deadline module itself (and tests) are exempt by path
    bare = """
    def helper():
        check_deadline("decode")
    """
    assert _codes(bare, path="opensim_tpu/resilience/deadline.py", rules=["deadline-span"]) == []
    assert _codes(bare, path="tests/test_x.py", rules=["deadline-span"]) == []


# ---------------------------------------------------------------------------
# OSL801 unsupervised-watch-loop
# ---------------------------------------------------------------------------


def test_watch_loop_flags_while_true_reconnect():
    src = """
    def follow(client):
        while True:
            try:
                for ev in client.watch("pods", rv):
                    handle(ev)
            except OSError:
                continue                 # reconnect forever, no bound
    """
    assert _codes(src, rules=["unsupervised-watch-loop"]) == ["OSL801"]


def test_watch_loop_flags_bare_stream_loop():
    src = """
    def tail(source):
        while True:
            consume(source.stream())
    """
    assert _codes(src, rules=["unsupervised-watch-loop"]) == ["OSL801"]


def test_watch_loop_accepts_retry_call_and_supervised_loops():
    src = """
    from opensim_tpu.resilience.retry import retry_call

    def follow(client, stop):
        while not stop.is_set():          # supervised condition: fine
            for ev in client.watch("pods", rv):
                handle(ev)

    def follow2(client):
        while True:                       # bounded via retry_call: fine
            stream = retry_call(lambda: client.watch("pods", rv), attempts=5)
            for ev in stream:
                handle(ev)

    def spin():
        while True:                       # no watch/stream call: OSL801 silent
            work()
    """
    assert _codes(src, rules=["unsupervised-watch-loop"]) == []


def test_watch_loop_suppression():
    src = """
    def follow(client):
        # opensim-lint: disable=unsupervised-watch-loop
        while True:
            consume(client.watch("pods"))
    """
    assert _codes(src, rules=["unsupervised-watch-loop"]) == []


# ---------------------------------------------------------------------------
# OSL901 reason-literal
# ---------------------------------------------------------------------------

def test_reason_literal_flags_inline_strings():
    src = """
    def decode(pod, node):
        ups = []
        ups.append(UnscheduledPod(pod, "no nodes matched"))
        ups.append(UnscheduledPod(pod, f'node "{node}" not found'))
        ups.append(UnscheduledPod(pod, reason="0/%d nodes" % 3))
        ups.append(UnscheduledPod(pod, "node {} gone".format(node)))
        return ups
    """
    assert _codes(src, rules=["reason-literal"]) == ["OSL901"] * 4


def test_reason_literal_accepts_registry_helpers_and_variables():
    src = """
    from opensim_tpu.engine import reasons

    def decode(pod, node, msg, custom):
        ups = [
            UnscheduledPod(pod, reasons.node_not_found(node)),
            UnscheduledPod(pod, reasons.preempted("ns", "hi")),
            UnscheduledPod(pod, reasons.render_unschedulable(4, [])),
            UnscheduledPod(pod, msg),
            UnscheduledPod(pod, custom[3]),
        ]
        return ups
    """
    assert _codes(src, rules=["reason-literal"]) == []


def test_reason_literal_repo_is_clean():
    import os

    root = os.path.join(os.path.dirname(__file__), "..", "opensim_tpu")
    findings = [f for f in lint_paths([root]) if f.code == "OSL901"]
    assert findings == [], [f"{f.path}:{f.line}" for f in findings]


# ---------------------------------------------------------------------------
# OSL1001 admission-lock-io (ISSUE 8 satellite)
# ---------------------------------------------------------------------------


def test_admission_lock_io_flags_blocking_calls_under_lock():
    src = """
    import time, urllib.request

    class Controller:
        def submit(self, t):
            with self._cond:
                time.sleep(0.1)
                self._queue.append(t)
                self._cond.notify()

        def drain(self):
            with self.lock:
                urllib.request.urlopen("http://x")

        def join_under_lock(self, fut):
            with self._lock:
                fut.result(timeout=3)
    """
    codes = _codes(src, path="opensim_tpu/server/admission.py",
                   rules=["admission-lock-io"])
    assert codes == ["OSL1001"] * 3


def test_admission_lock_io_allows_cond_wait_and_queue_work():
    src = """
    class Controller:
        def consume(self):
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                item = self._queue.popleft()
                self._cond.notify_all()
            return item

        def other_wait_is_flagged(self, ev):
            with self._cond:
                ev.wait()
    """
    codes = _codes(src, path="opensim_tpu/server/admission.py",
                   rules=["admission-lock-io"])
    # cond.wait() on the held condition is the one legal wait; ev.wait()
    # under the lock is the convoy maker
    assert codes == ["OSL1001"]


def test_admission_lock_io_scoped_to_serving_modules():
    src = """
    import time

    def elsewhere(self):
        with self.lock:
            time.sleep(1)
    """
    assert _codes(src, path="opensim_tpu/engine/simulator.py",
                  rules=["admission-lock-io"]) == []


def test_admission_lock_io_repo_is_clean():
    import os

    root = os.path.join(os.path.dirname(__file__), "..", "opensim_tpu")
    findings = [f for f in lint_paths([root]) if f.code == "OSL1001"]
    assert findings == [], [f"{f.path}:{f.line}" for f in findings]


# ---------------------------------------------------------------------------
# OSL1301 journal-discipline (ISSUE 11)
# ---------------------------------------------------------------------------


def test_journal_discipline_flags_foreign_writes_and_fsync():
    # a journal path opened for writing outside server/journal.py
    assert _codes(
        'f = open("state/journal-00000001.seg", "ab")\n',
        rules=["journal-discipline"],
    ) == ["OSL1301"]
    assert _codes(
        'f = open(self.journal_path, mode="w")\n',
        rules=["journal-discipline"],
    ) == ["OSL1301"]
    # any os.fsync outside the journal module
    assert _codes(
        "import os\nos.fsync(fd)\n", rules=["journal-discipline"]
    ) == ["OSL1301"]


def test_journal_discipline_allows_ordinary_io():
    # read-mode journal opens and unrelated writes stay legal
    assert _codes(
        'f = open("state/journal-00000001.seg", "rb")\n',
        rules=["journal-discipline"],
    ) == []
    assert _codes('f = open("report.txt", "w")\n', rules=["journal-discipline"]) == []
    # tests are excluded: they corrupt journals on purpose
    assert _codes(
        "import os\nos.fsync(3)\n",
        path="tests/test_journal.py",
        rules=["journal-discipline"],
    ) == []


def test_journal_discipline_unchecksummed_write_inside_journal_module():
    src = """
    class Journal:
        def _write_framed(self, payload):
            self._f.write(payload)  # THE framing path: legal

        def _sneaky(self, b):
            self._f.write(b)  # bypasses the crc framing
    """
    assert _codes(
        src, path="opensim_tpu/server/journal.py", rules=["journal-discipline"]
    ) == ["OSL1301"]


def test_journal_discipline_suppression():
    src = 'import os\nos.fsync(fd)  # opensim-lint: disable=journal-discipline\n'
    assert _codes(src, rules=["journal-discipline"]) == []
