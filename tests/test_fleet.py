"""Multi-process serving fleet (ISSUE 15): shared-memory twin publication.

The load-bearing gates, all in-process (the subprocess end-to-end run —
boot, crash/respawn, SO_REUSEPORT sharing — lives in ``make
loadgen-smoke``):

- seqlock: a reader attaching DURING generation swaps never observes a
  torn view (generation and payload always agree);
- lifecycle: close/atexit/hard-crash leave no ``/dev/shm`` segments, and
  an exiting READER never destroys the owner's live segments;
- parity: placements simulated through an attached publication are
  bit-identical to the owner's own warm-base path;
- delta: unchanged buffers keep their content-keyed segments across
  generations, and the reader reuses its attachments.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from opensim_tpu.engine import prepcache
from opensim_tpu.engine.simulator import AppResource, prepare, simulate
from opensim_tpu.models import ResourceTypes, fixtures as fx
from opensim_tpu.server.fleet import (
    ControlBlock,
    FleetReader,
    FleetTwinClient,
    TornGeneration,
    TwinPublisher,
)


def _shm_names(token: str):
    try:
        return [f for f in os.listdir("/dev/shm") if token in f]
    except FileNotFoundError:  # pragma: no cover - non-linux
        pytest.skip("/dev/shm not available")


def _cluster(n_nodes: int = 6, with_pod: bool = True) -> ResourceTypes:
    rt = ResourceTypes()
    for i in range(n_nodes):
        rt.nodes.append(
            fx.make_fake_node(
                f"n{i:03d}", "16", "64Gi", "110",
                fx.with_labels({"topology.kubernetes.io/zone": f"z{i % 3}"}),
            )
        )
    if with_pod:
        rt.pods.append(
            fx.make_fake_pod("pinned", "100m", "128Mi", fx.with_node_name("n000"))
        )
    return rt


def _base_entry(cluster: ResourceTypes) -> prepcache.CacheEntry:
    return prepcache.CacheEntry("t|base", prepare(cluster, []))


def _apps(name: str = "app-x", replicas: int = 3, cpu: str = "500m"):
    rt = ResourceTypes()
    rt.add(fx.make_fake_deployment(name, replicas, cpu, "1Gi"))
    return [AppResource("deploy", rt)]


def _placements(res):
    return (
        sorted((ns.node.metadata.name, len(ns.pods)) for ns in res.node_status if ns.pods),
        sorted(u.reason for u in res.unscheduled_pods),
    )


def _derive_and_simulate(entry, cluster, apps):
    with entry.lock:
        entry.restore()
        derived = prepcache.derive_with_apps(entry.prep, cluster, apps, base_entry=entry)
        drop = prepcache.pad_drop_mask(entry.base_drop, len(derived.ordered))
        try:
            return simulate(cluster, apps, prep=derived, drop_pods=drop)
        finally:
            entry.restore()


# ---------------------------------------------------------------------------
# seqlock / torn-generation
# ---------------------------------------------------------------------------


def test_control_block_roundtrip_and_poll():
    cb = ControlBlock(create=True)
    try:
        assert cb.poll() is None  # nothing published yet
        cb.write(7, {"fingerprint": "abc", "arrays": [], "blob": "b"})
        reader = ControlBlock(name=cb.name, create=False)
        assert reader.poll() == 7
        gen, payload, seq = reader.read()
        assert gen == 7 and payload["fingerprint"] == "abc" and seq % 2 == 0
        reader.close()
    finally:
        cb.unlink()
        cb.close()


def test_reader_never_observes_torn_generation():
    """Attach during continuous generation swaps: every successful attach
    must be self-consistent — the published array content encodes the
    generation it was written for, and both must agree."""
    pub = TwinPublisher()
    stop = threading.Event()
    errors = []

    def writer():
        gen = 0
        while not stop.is_set():
            gen += 1
            # the array content is a function of the generation: a torn
            # view (payload of gen k, arrays of gen j) cannot self-agree
            parts = {"stamp": np.full((64,), gen, dtype=np.int64)}
            pub.publish(gen, {"gen": gen}, parts)
            time.sleep(0.001)

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    try:
        reader = FleetReader(pub.control.name, retries=64)
        attached = 0
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            try:
                gen, payload, obj = reader.attach()
            except TornGeneration:
                continue  # bounded: counted, never a torn view
            attached += 1
            stamp = obj["parts"]["stamp"]
            if obj["cluster"]["gen"] != gen or not (stamp == gen).all():
                errors.append((gen, obj["cluster"]["gen"], stamp[0]))
        assert attached > 10
        assert not errors, f"torn views observed: {errors[:3]}"
    finally:
        stop.set()
        t.join(timeout=5.0)
        pub.close()


def test_attach_retries_exhausted_is_typed():
    cb = ControlBlock(create=True)
    try:
        # leave seq odd: a publish permanently in flight
        cb.write(1, {"blob": "x", "arrays": []})
        import struct

        cb._seq += 1
        struct.pack_into("<Q", cb._shm.buf, 8, cb._seq)
        reader = FleetReader(cb.name, retries=3)
        with pytest.raises(TornGeneration):
            reader.attach()
        assert reader.retries_exhausted_total == 1
        reader.close()
    finally:
        cb.unlink()
        cb.close()


# ---------------------------------------------------------------------------
# lifecycle: no leaked /dev/shm segments, reader never destroys owner state
# ---------------------------------------------------------------------------


def test_close_unlinks_every_segment():
    cluster = _cluster()
    base = _base_entry(cluster)
    with base.lock:
        base.restore()
        parts = prepcache.publication_parts(base)
    pub = TwinPublisher()
    token = pub.token
    pub.publish(1, cluster, parts)
    assert _shm_names(token)  # segments exist while live
    pub.close()
    assert _shm_names(token) == []
    pub.close()  # idempotent


def test_owner_hard_crash_leaves_no_segments(tmp_path):
    """SIGKILL the owner mid-publication: the resource tracker (a separate
    process that survives the kill) must unlink everything — /dev/shm
    hygiene does not depend on atexit running."""
    script = tmp_path / "owner.py"
    script.write_text(
        "import os, sys, time\n"
        "import numpy as np\n"
        "sys.path.insert(0, %r)\n"
        "from opensim_tpu.server.fleet import TwinPublisher\n"
        "pub = TwinPublisher()\n"
        "pub.publish(1, {'x': 1}, {'a': np.zeros(1024)})\n"
        "print(pub.token, flush=True)\n"
        "time.sleep(60)\n" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    proc = subprocess.Popen(
        [sys.executable, str(script)], stdout=subprocess.PIPE,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    try:
        token = proc.stdout.readline().decode().strip()
        assert token and _shm_names(token)
        proc.kill()
        proc.wait(timeout=30)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and _shm_names(token):
            time.sleep(0.2)
        assert _shm_names(token) == [], "resource tracker left segments behind"
    finally:
        if proc.poll() is None:  # pragma: no cover - cleanup on failure
            proc.kill()


def test_reader_exit_does_not_destroy_owner_segments(tmp_path):
    """A worker that attaches and exits must leave the owner's segments
    intact (the resource-tracker unregister in ``_attach``): a later
    reader still attaches the same generation."""
    pub = TwinPublisher()
    try:
        pub.publish(3, {"ok": True}, {"a": np.arange(128, dtype=np.int64)})
        code = (
            "import sys\n"
            f"sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})\n"
            "from opensim_tpu.server.fleet import FleetReader\n"
            f"r = FleetReader({pub.control.name!r})\n"
            "gen, payload, obj = r.attach()\n"
            "assert gen == 3 and obj['cluster']['ok'] is True\n"
            "print('attached', flush=True)\n"
        )
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, timeout=120,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        assert out.returncode == 0, out.stderr.decode()[-2000:]
        # the owner's publication must still be fully attachable
        r2 = FleetReader(pub.control.name)
        gen, _payload, obj = r2.attach()
        assert gen == 3 and (obj["parts"]["a"] == np.arange(128)).all()
        r2.close()
    finally:
        pub.close()


# ---------------------------------------------------------------------------
# parity: attached placements == owner placements
# ---------------------------------------------------------------------------


def test_attached_placements_bit_identical():
    cluster = _cluster()
    base = _base_entry(cluster)
    with base.lock:
        base.restore()
        parts = prepcache.publication_parts(base)
    pub = TwinPublisher()
    try:
        pub.publish(5, cluster, parts)
        reader = FleetReader(pub.control.name)
        gen, payload, obj = reader.attach()
        assert gen == 5
        entry = prepcache.entry_from_publication("fleet|5|base", obj["parts"])
        # the reconstructed numpy views are zero-copy and read-only
        assert not entry.prep.ec_np.alloc.flags.writeable
        for apps in (_apps(), _apps("huge", 1, "640")):  # placed + unschedulable
            solo = _placements(_derive_and_simulate(base, cluster, apps))
            fleet = _placements(_derive_and_simulate(entry, obj["cluster"], apps))
            assert solo == fleet
        reader.close()
    finally:
        pub.close()


def test_base_drop_mask_round_trips():
    """The twin's event-deleted pods (base_drop) must survive publication:
    a worker's simulate excludes them exactly like the owner's."""
    cluster = _cluster()
    base = _base_entry(cluster)
    with base.lock:
        base.restore()
        drop = np.zeros((len(base.prep.ordered),), dtype=bool)
        drop[0] = True  # the pinned pod was DELETED by a watch event
        base.base_drop = drop
        parts = prepcache.publication_parts(base)
    pub = TwinPublisher()
    try:
        pub.publish(6, cluster, parts)
        reader = FleetReader(pub.control.name)
        _gen, _payload, obj = reader.attach()
        entry = prepcache.entry_from_publication("fleet|6|base", obj["parts"])
        assert entry.base_drop is not None and entry.base_drop[0]
        solo = _placements(_derive_and_simulate(base, cluster, _apps()))
        fleet = _placements(_derive_and_simulate(entry, obj["cluster"], _apps()))
        assert solo == fleet
        reader.close()
    finally:
        pub.close()


# ---------------------------------------------------------------------------
# delta publication
# ---------------------------------------------------------------------------


def test_unchanged_buffers_keep_segments_across_generations():
    cluster = _cluster()
    base = _base_entry(cluster)
    with base.lock:
        base.restore()
        parts = prepcache.publication_parts(base)
    pub = TwinPublisher()
    try:
        p1 = pub.publish(1, cluster, parts)
        p2 = pub.publish(2, cluster, parts)
        n1 = {a[0] for a in p1["arrays"]}
        n2 = {a[0] for a in p2["arrays"]}
        assert n1 == n2  # identical content: every segment reused
        reader = FleetReader(pub.control.name)
        reader.attach()
        reuse0 = reader.segment_reuse_total
        pub.publish(3, cluster, parts)
        gen, _p, _o = reader.attach()
        assert gen == 3
        assert reader.segment_reuse_total > reuse0  # attachments reused too
        reader.close()
    finally:
        pub.close()


def test_gc_drops_segments_outside_keep_window():
    pub = TwinPublisher(keep_generations=2)
    try:
        names = []
        for gen in range(1, 5):
            p = pub.publish(gen, {"g": gen}, {"a": np.full(64, gen, np.int64)})
            names.append({a[0] for a in p["arrays"]})
        live = {n for f in _shm_names(pub.token) for n in [f]}
        # generation 1/2's distinct arrays are gone; 3/4's remain
        assert not any(n in live for n in names[0] - names[2] - names[3])
        assert all(n in live for n in names[3])
    finally:
        pub.close()


# ---------------------------------------------------------------------------
# the worker-side client
# ---------------------------------------------------------------------------


def test_fleet_twin_client_serves_and_swaps_generations():
    cluster = _cluster()
    base = _base_entry(cluster)
    with base.lock:
        base.restore()
        parts = prepcache.publication_parts(base)
    pub = TwinPublisher()
    try:
        pub.publish(1, cluster, parts, state="live", stale=False)
        cache = prepcache.PrepareCache()
        client = FleetTwinClient(pub.control.name, prep_cache=cache)
        assert client.start(wait_s=10.0)
        got = client.serving_snapshot()
        assert got is not None
        cl, key, stale = got
        assert key == "fleet|1" and stale is False
        assert cache.get("fleet|1|base") is not None
        assert client.state() == "fleet-live"
        # generation swap: new key served, old lineage invalidated
        pub.publish(2, cluster, parts, state="degraded", stale=True)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            cl, key, stale = client.serving_snapshot()
            if key == "fleet|2":
                break
        assert key == "fleet|2" and stale is True
        assert cache.get("fleet|2|base") is not None
        assert cache.get("fleet|1|base") is None
        lines = client.metrics_lines()
        assert any(l.startswith("simon_fleet_attaches_total 2") for l in lines)
        assert any(
            l.startswith("simon_fleet_attach_retries_exhausted_total 0")
            for l in lines
        )
        client.stop()
    finally:
        pub.close()


def test_no_prep_publication_still_serves_cluster():
    """A twin with no schedulable pods publishes parts=None; the worker
    serves the cluster and the REST layer's own bootstrap covers prep."""
    cluster = _cluster(with_pod=False)
    pub = TwinPublisher()
    try:
        pub.publish(4, cluster, None)
        cache = prepcache.PrepareCache()
        client = FleetTwinClient(pub.control.name, prep_cache=cache)
        assert client.start(wait_s=10.0)
        cl, key, _stale = client.serving_snapshot()
        assert key == "fleet|4" and len(cl.nodes) == len(cluster.nodes)
        assert cache.get("fleet|4|base") is None  # nothing published to seed
        client.stop()
    finally:
        pub.close()


def test_same_generation_republish_reaches_workers():
    """A staleness/state flip on a quiet twin republishes at the SAME
    generation; workers must refresh their payload (the control seq is
    the change detector) or degraded responses lose their stale tag."""
    cluster = _cluster()
    base = _base_entry(cluster)
    with base.lock:
        base.restore()
        parts = prepcache.publication_parts(base)
    pub = TwinPublisher()
    try:
        pub.publish(9, cluster, parts, state="live", stale=False)
        client = FleetTwinClient(pub.control.name, prep_cache=prepcache.PrepareCache())
        assert client.start(wait_s=10.0)
        _cl, key, stale = client.serving_snapshot()
        assert key == "fleet|9" and stale is False
        pub.publish(9, cluster, parts, state="degraded", stale=True)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            _cl, key, stale = client.serving_snapshot()
            if stale:
                break
        assert key == "fleet|9" and stale is True
        assert client.state() == "fleet-degraded"
        client.stop()
    finally:
        pub.close()
