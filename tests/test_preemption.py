"""Opt-in preemption pass — the DefaultPreemption PostFilter the reference
registers but can never exercise (its driver deletes unschedulable pods,
simulator.go:333-342). See opensim_tpu/engine/preemption.py."""

import pytest

from opensim_tpu.engine.simulator import AppResource, simulate
from opensim_tpu.models import ResourceTypes
from opensim_tpu.models import fixtures as fx


def _cluster(n=2, cpu="4", mem="8Gi"):
    rt = ResourceTypes()
    for i in range(n):
        rt.nodes.append(fx.make_fake_node(f"n{i}", cpu, mem))
    return rt


def test_high_priority_pod_lands_via_eviction():
    cluster = _cluster(n=1)
    app = ResourceTypes()
    # two low-priority pods fill the node; the late high-priority pod evicts one
    app.pods.append(fx.make_fake_pod("low-a", "2", "2Gi", fx.with_priority(10)))
    app.pods.append(fx.make_fake_pod("low-b", "2", "2Gi", fx.with_priority(20)))
    app.pods.append(fx.make_fake_pod("vip", "2", "2Gi", fx.with_priority(1000)))

    res_off = simulate(cluster, [AppResource("a", app)])
    assert {u.pod.metadata.name for u in res_off.unscheduled_pods} == {"vip"}

    res_on = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    placed = {p.metadata.name for ns in res_on.node_status for p in ns.pods}
    assert "vip" in placed
    # the LOWEST-priority victim is chosen
    assert {u.pod.metadata.name for u in res_on.unscheduled_pods} == {"low-a"}
    assert "preempted by higher-priority pod" in res_on.unscheduled_pods[0].reason
    assert "vip" in res_on.unscheduled_pods[0].reason


def test_preemption_respects_priority_order_and_caps():
    cluster = _cluster(n=1)
    app = ResourceTypes()
    # equal-priority pod cannot preempt (victims must be strictly lower)
    app.pods.append(fx.make_fake_pod("peer-a", "3", "2Gi", fx.with_priority(50)))
    app.pods.append(fx.make_fake_pod("peer-b", "3", "2Gi", fx.with_priority(50)))
    res = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    assert len(res.unscheduled_pods) == 1  # no eviction among equals

    # zero-priority unschedulable pods never preempt
    app2 = ResourceTypes()
    app2.pods.append(fx.make_fake_pod("filler", "3", "2Gi", fx.with_priority(5)))
    app2.pods.append(fx.make_fake_pod("plain", "3", "2Gi"))
    res2 = simulate(cluster, [AppResource("a", app2)], enable_preemption=True)
    assert {u.pod.metadata.name for u in res2.unscheduled_pods} == {"plain"}


def test_preemption_takes_lowest_priority_victims_first():
    cluster = _cluster(n=1, cpu="6")
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("low-a", "2", "1Gi", fx.with_priority(10)))
    app.pods.append(fx.make_fake_pod("low-b", "2", "1Gi", fx.with_priority(20)))
    app.pods.append(fx.make_fake_pod("mid", "2", "1Gi", fx.with_priority(50)))
    app.pods.append(fx.make_fake_pod("vip", "4", "2Gi", fx.with_priority(100)))
    res = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    placed = {p.metadata.name for ns in res.node_status for p in ns.pods}
    # vip frees 4 cpu by evicting the two LOWEST-priority pods; mid survives
    assert "vip" in placed and "mid" in placed
    assert {u.pod.metadata.name for u in res.unscheduled_pods} == {"low-a", "low-b"}


def test_forced_pods_are_never_victims():
    cluster = _cluster(n=1)
    cluster.pods.append(fx.make_fake_pod("resident", "3", "4Gi", fx.with_priority(1), fx.with_node_name("n0")))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("vip", "3", "4Gi", fx.with_priority(100)))
    res = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    # the pre-bound resident stays; vip remains unscheduled with a kube reason
    assert {u.pod.metadata.name for u in res.unscheduled_pods} == {"vip"}
    assert "Insufficient" in res.unscheduled_pods[0].reason


def test_port_holding_victim_frees_the_port():
    """A high-priority pod needing a host port evicts the lower-priority
    port holder (round-2b: ports are modeled through the conflict matrix)."""
    cluster = _cluster(n=1)
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("holder", "1", "1Gi", fx.with_priority(5),
                                     fx.with_host_ports([8080])))
    app.pods.append(fx.make_fake_pod("vip", "1", "1Gi", fx.with_priority(500),
                                     fx.with_host_ports([8080])))
    res = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    placed = {p.metadata.name for ns in res.node_status for p in ns.pods}
    assert "vip" in placed
    unsched = {u.pod.metadata.name: u.reason for u in res.unscheduled_pods}
    assert set(unsched) == {"holder"}
    assert "preempted by higher-priority pod" in unsched["holder"]


def test_gpu_victim_frees_devices_and_preemptor_gets_annotation():
    from opensim_tpu.models.objects import ANNO_GPU_INDEX

    cluster = ResourceTypes()
    cluster.nodes.append(
        fx.make_fake_node(
            "g0", "8", "16Gi", "110",
            fx.with_allocatable({"alibabacloud.com/gpu-mem": "16Gi",
                                 "alibabacloud.com/gpu-count": "2"}),
        )
    )
    gpu_req = fx.with_annotations({"alibabacloud.com/gpu-mem": "8Gi",
                                   "alibabacloud.com/gpu-count": "2"})
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("tenant", "1", "1Gi", fx.with_priority(5), gpu_req))
    app.pods.append(fx.make_fake_pod("vip", "1", "1Gi", fx.with_priority(500), gpu_req))
    res = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    placed = {p.metadata.name: p for ns in res.node_status for p in ns.pods}
    assert "vip" in placed
    assert placed["vip"].metadata.annotations.get(ANNO_GPU_INDEX) == "0-1"
    assert {u.pod.metadata.name for u in res.unscheduled_pods} == {"tenant"}


def test_storage_preemptor_lands_on_storage_node():
    """An open-local preemptor can evict a plain resource hog from the only
    storage-capable node (victims free cpu/mem; the VG must fit as-is)."""
    cluster = ResourceTypes()
    cluster.nodes.append(
        fx.make_fake_node(
            "s0", "4", "8Gi", "110",
            fx.with_node_local_storage(vgs=[{"name": "pool", "capacity": 100 * 1024**3}]),
        )
    )
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("hog", "4", "2Gi", fx.with_priority(5)))
    import json

    payload = json.dumps({"volumes": [{"size": str(10 * 1024**3), "kind": "LVM",
                                       "scName": "open-local-lvm"}]})
    app.pods.append(
        fx.make_fake_pod("db", "2", "2Gi", fx.with_priority(500),
                         fx.with_pod_local_storage(payload))
    )
    res = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    placed = {p.metadata.name for ns in res.node_status for p in ns.pods}
    assert "db" in placed
    assert {u.pod.metadata.name for u in res.unscheduled_pods} == {"hog"}


def test_cascading_replacement_rehomes_the_victim():
    """Eviction from a pinned-affinity node re-places the victim on the
    other node instead of reporting it unschedulable (round-2b cascade)."""
    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n0", "4", "8Gi", "110", fx.with_labels({"disk": "ssd"})))
    cluster.nodes.append(fx.make_fake_node("n1", "4", "8Gi"))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("tenant", "3", "2Gi", fx.with_priority(5)))
    app.pods.append(
        fx.make_fake_pod("vip", "3", "2Gi", fx.with_priority(500),
                         fx.with_node_selector({"disk": "ssd"}))
    )
    res = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    placed = {p.metadata.name: ns.node.metadata.name
              for ns in res.node_status for p in ns.pods}
    # vip takes the ssd node; the displaced tenant cascades onto n1
    assert placed.get("vip") == "n0"
    assert placed.get("tenant") == "n1"
    assert not res.unscheduled_pods


def test_gpu_preemption_on_xla_path(monkeypatch):
    """Same GPU eviction through the XLA scan (native disabled): the
    read-only jax gpu_take buffer must be copied before mutation."""
    monkeypatch.setenv("OPENSIM_DISABLE_NATIVE", "1")
    test_gpu_victim_frees_devices_and_preemptor_gets_annotation()


def test_spread_constrained_preemptor_still_preempts():
    """A soft-spread selector registers selector id 0; the dummy anti-term
    row must not be mistaken for a real anti-affinity target."""
    cluster = _cluster(n=1)
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("low", "3", "2Gi", fx.with_priority(5)))
    app.deployments.append(
        fx.make_fake_deployment(
            "vip", 1, "3", "2Gi", fx.with_priority(500),
            fx.with_topology_spread(
                [
                    {
                        "maxSkew": 1,
                        "topologyKey": "kubernetes.io/hostname",
                        "whenUnsatisfiable": "ScheduleAnyway",
                        "labelSelector": {"matchLabels": {"app": "vip"}},
                    }
                ]
            ),
        )
    )
    res = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    placed = {p.metadata.name for ns in res.node_status for p in ns.pods}
    assert any(n.startswith("vip") for n in placed)
    assert {u.pod.metadata.name for u in res.unscheduled_pods} == {"low"}


def test_cascade_skips_anti_affinity_victims():
    """An evicted victim with its own required anti-affinity must stay
    preempted rather than cascade onto a node that violates it."""
    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n0", "4", "8Gi", "110", fx.with_labels({"disk": "ssd"})))
    cluster.nodes.append(fx.make_fake_node("n1", "4", "8Gi", "110", fx.with_labels({"disk": "hdd"})))
    app = ResourceTypes()
    # db is pinned to n1 (the victim's only alternative) and repels it
    app.pods.append(fx.make_fake_pod("db", "1", "1Gi", fx.with_labels({"app": "db"}),
                                     fx.with_node_selector({"disk": "hdd"})))
    anti = fx.with_affinity(
        {
            "podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {
                        "labelSelector": {"matchLabels": {"app": "db"}},
                        "topologyKey": "kubernetes.io/hostname",
                    }
                ]
            }
        }
    )
    app.pods.append(fx.make_fake_pod("tenant", "3", "2Gi", fx.with_priority(5), anti))
    vip_app = ResourceTypes()
    vip_app.pods.append(
        fx.make_fake_pod("vip", "3", "2Gi", fx.with_priority(500),
                         fx.with_node_selector({"disk": "ssd"}))
    )
    res = simulate(cluster, [AppResource("a", app), AppResource("b", vip_app)],
                   enable_preemption=True)
    placed = {p.metadata.name: ns.node.metadata.name
              for ns in res.node_status for p in ns.pods}
    unsched = {u.pod.metadata.name: u.reason for u in res.unscheduled_pods}
    assert placed.get("vip") == "n0"
    # tenant must NOT cascade next to db; it stays preempted
    assert "tenant" in unsched and "preempted" in unsched["tenant"]


def test_partial_state_arguments_rejected():
    """port_used/gpu_free/vg_free/dev_free/gpu_take must be passed together —
    partial state would mix initial and final occupancy (ADVICE r2)."""
    import numpy as np
    import pytest

    from opensim_tpu.engine import preemption
    from opensim_tpu.engine.simulator import prepare

    cluster = _cluster(n=1)
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("p", "1", "1Gi", fx.with_priority(5)))
    prep = prepare(cluster, [AppResource("a", app)])
    used = np.array(np.asarray(prep.st0.used), copy=True)
    alloc = np.asarray(prep.ec_np.alloc)
    chosen = np.array([-1], dtype=np.int64)
    with pytest.raises(ValueError, match="all or none"):
        preemption.preempt_pass(
            prep, chosen, cluster.nodes, used, alloc,
            port_used=np.array(np.asarray(prep.st0.port_used), copy=True),
        )
    # all-none still works
    out, victims = preemption.preempt_pass(prep, chosen, cluster.nodes, used, alloc)
    assert victims == {}


def _pdb(name, match_labels, min_available=None, max_unavailable=None, ns="default"):
    from opensim_tpu.models.objects import ObjectMeta, RawObject

    spec = {"selector": {"matchLabels": match_labels}}
    if min_available is not None:
        spec["minAvailable"] = min_available
    if max_unavailable is not None:
        spec["maxUnavailable"] = max_unavailable
    return RawObject(
        kind="PodDisruptionBudget",
        metadata=ObjectMeta(name=name, namespace=ns),
        raw={"metadata": {"name": name, "namespace": ns}, "spec": spec},
    )


def test_pdb_saves_victim():
    """A PDB with no disruption allowance makes its pods last-resort
    victims (default_preemption.go:642): with an unprotected alternative
    victim available, the protected pod survives."""
    cluster = _cluster(n=1, cpu="4")
    cluster.pdbs.append(_pdb("guard", {"app": "protected"}, min_available=1))
    app = ResourceTypes()
    # protected (matches the PDB, minAvailable=1 of 1 -> 0 disruptions) and
    # plain both evictable by priority; only plain should be evicted
    app.pods.append(
        fx.make_fake_pod("protected", "2", "1Gi", fx.with_priority(10),
                         fx.with_labels({"app": "protected"}))
    )
    app.pods.append(fx.make_fake_pod("plain", "2", "1Gi", fx.with_priority(10)))
    app.pods.append(fx.make_fake_pod("vip", "2", "1Gi", fx.with_priority(1000)))
    res = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    placed = {p.metadata.name for ns in res.node_status for p in ns.pods}
    unsched = {u.pod.metadata.name for u in res.unscheduled_pods}
    assert "vip" in placed
    assert "protected" in placed, "PDB-covered pod must be reprieved"
    assert unsched == {"plain"}


def test_pdb_exhausted_budget_still_preempts_when_no_alternative():
    """When every candidate victim violates its PDB, preemption still
    proceeds (kube treats PDB as a preference ladder, not a hard block)."""
    cluster = _cluster(n=1, cpu="4")
    cluster.pdbs.append(_pdb("guard", {"app": "db"}, min_available=2))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("db-0", "2", "1Gi", fx.with_priority(10),
                                     fx.with_labels({"app": "db"})))
    app.pods.append(fx.make_fake_pod("db-1", "2", "1Gi", fx.with_priority(20),
                                     fx.with_labels({"app": "db"})))
    app.pods.append(fx.make_fake_pod("vip", "2", "1Gi", fx.with_priority(1000)))
    res = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    placed = {p.metadata.name for ns in res.node_status for p in ns.pods}
    assert "vip" in placed
    # the lowest-priority PDB victim is taken
    assert {u.pod.metadata.name for u in res.unscheduled_pods} == {"db-0"}


def test_pdb_ranking_prefers_node_without_violation():
    """pickOneNodeForPreemption criterion #1: among feasible candidate
    nodes, the one whose victims violate fewer PDBs wins even when the
    other node's victim has lower priority."""
    cluster = _cluster(n=2, cpu="4")
    cluster.pdbs.append(_pdb("guard", {"app": "prot"}, min_available=1))
    app = ResourceTypes()
    # n0 gets the protected pod (lower priority), n1 the plain pod: the
    # scheduler spreads them; vip must land on the plain pod's node
    app.pods.append(fx.make_fake_pod("prot", "3", "1Gi", fx.with_priority(5),
                                     fx.with_labels({"app": "prot"})))
    app.pods.append(fx.make_fake_pod("plain", "3", "1Gi", fx.with_priority(50)))
    app.pods.append(fx.make_fake_pod("vip", "3", "1Gi", fx.with_priority(1000)))
    res = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    unsched = {u.pod.metadata.name for u in res.unscheduled_pods}
    placed = {p.metadata.name: ns.node.metadata.name
              for ns in res.node_status for p in ns.pods}
    assert "vip" in placed
    assert "prot" in placed, "protected pod's node must not be chosen"
    assert unsched == {"plain"}


def test_storage_holding_victim_released_exactly():
    """A victim holding open-local storage is evictable; its VG bytes and
    exclusive device return to the pool and the preemptor (also a storage
    consumer) packs into the freed capacity."""
    cluster = ResourceTypes()
    cluster.nodes.append(
        fx.make_fake_node(
            "n0", "4", "8Gi", "110",
            fx.with_node_local_storage(
                vgs=[{"name": "pool0", "capacity": 100 * 1024**3}],
                devices=[{"device": "/dev/vdb", "capacity": 50 * 1024**3, "mediaType": "ssd"}],
            ),
        )
    )
    import json

    def lvm(size):
        return fx.with_pod_local_storage(json.dumps(
            {"volumes": [{"size": str(size), "kind": "LVM", "scName": "open-local-lvm"}]}
        ))

    app = ResourceTypes()
    # the low pod consumes 90Gi of the 100Gi VG; vip needs 80Gi
    app.pods.append(fx.make_fake_pod("low", "1", "1Gi", fx.with_priority(5),
                                     lvm(90 * 1024**3)))
    app.pods.append(fx.make_fake_pod("vip", "1", "1Gi", fx.with_priority(1000),
                                     lvm(80 * 1024**3)))
    res = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    placed = {p.metadata.name for ns in res.node_status for p in ns.pods}
    unsched = {u.pod.metadata.name for u in res.unscheduled_pods}
    assert "vip" in placed, f"vip should evict low and take its VG space (unsched={unsched})"
    assert "low" in unsched


# ---------------------------------------------------------------------------
# r4: lifted skips — affinity/spread preemptors re-evaluated post-eviction,
# selector-matched victims allowed (VERDICT r3 #6) + ADVICE fixes
# ---------------------------------------------------------------------------


def test_anti_affinity_preemptor_evicts_its_blocker():
    """A preemptor with required anti-affinity vs a lower-priority blocker:
    evicting the blocker REMOVES the violation, so preemption must land it
    (the old pass skipped all interpod-bearing preemptors)."""
    cluster = _cluster(n=1, cpu="8")
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod(
        "blocker", "2", "2Gi", fx.with_priority(10),
        fx.with_pod_labels({"team": "red"}),
    ))
    app.pods.append(fx.make_fake_pod(
        "vip", "2", "2Gi", fx.with_priority(1000),
        fx.with_affinity({"podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [{
                "labelSelector": {"matchLabels": {"team": "red"}},
                "topologyKey": "kubernetes.io/hostname",
            }]}}),
    ))
    res_off = simulate(cluster, [AppResource("a", app)])
    assert {u.pod.metadata.name for u in res_off.unscheduled_pods} == {"vip"}
    res = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    placed = {p.metadata.name for ns in res.node_status for p in ns.pods}
    assert "vip" in placed
    assert {u.pod.metadata.name for u in res.unscheduled_pods} == {"blocker"}


def test_affinity_anchored_preemptor_rejected_like_kube():
    """A preemptor whose required affinity is anchored by a candidate
    victim: selectVictimsOnNode removes ALL lower-priority pods BEFORE the
    filter check (default_preemption.go), so the anchor is hypothetically
    gone and the node is rejected — kube-faithful, asserted here."""
    cluster = _cluster(n=1, cpu="6")
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod(
        "anchor", "2", "2Gi", fx.with_priority(10),
        fx.with_pod_labels({"role": "db"}),
    ))
    app.pods.append(fx.make_fake_pod(
        "filler", "3", "2Gi", fx.with_priority(10),
    ))
    app.pods.append(fx.make_fake_pod(
        "vip", "2", "2Gi", fx.with_priority(1000),
        fx.with_affinity({"podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [{
                "labelSelector": {"matchLabels": {"role": "db"}},
                "topologyKey": "kubernetes.io/hostname",
            }]}}),
    ))
    res = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    placed = {p.metadata.name for ns in res.node_status for p in ns.pods}
    # remove-all-first semantics: the hypothetical eviction of the anchor
    # fails the affinity filter, so no preemption happens on this node
    assert "vip" not in placed
    assert {"anchor", "filler"} <= placed


def test_hard_spread_preemptor_lands_post_eviction():
    """A preemptor with a DoNotSchedule spread constraint schedules via
    preemption when the eviction rebalances the skew."""
    rt = ResourceTypes()
    for i in range(2):
        rt.nodes.append(fx.make_fake_node(
            f"n{i}", "4", "8Gi", "110",
            fx.with_labels({"topology.kubernetes.io/zone": f"z{i}"}),
        ))
    app = ResourceTypes()
    # fill z1 so the spread pod's only skew-legal zone has no room
    app.pods.append(fx.make_fake_pod("filler", "4", "2Gi", fx.with_priority(10),
                                     fx.with_node_selector({})))
    app.pods[-1].spec.node_selector = {}
    app.pods[-1].raw.setdefault("spec", {})["nodeSelector"] = {}
    app.pods.append(fx.make_fake_pod(
        "spread-a", "1", "1Gi", fx.with_priority(1000),
        fx.with_pod_labels({"app": "s"}),
        fx.with_topology_spread([{
            "maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "s"}},
        }]),
    ))
    res = simulate(rt, [AppResource("a", app)], enable_preemption=True)
    placed = {p.metadata.name for ns in res.node_status for p in ns.pods}
    assert "spread-a" in placed


def test_selector_matched_victim_is_now_evictable():
    """A victim matched by another pod's affinity selector is evictable
    (IgnoredDuringExecution); the old pass froze every selector-matched
    pod as soon as any interpod feature existed in the workload."""
    cluster = _cluster(n=1, cpu="4")
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod(
        "anchored", "3", "2Gi", fx.with_priority(10),
        fx.with_pod_labels({"app": "web"}),
        # carries a PREFERRED term so interpod features exist in the stream
        fx.with_affinity({"podAffinity": {
            "preferredDuringSchedulingIgnoredDuringExecution": [{
                "weight": 10,
                "podAffinityTerm": {
                    "labelSelector": {"matchLabels": {"app": "web"}},
                    "topologyKey": "kubernetes.io/hostname",
                },
            }]}}),
    ))
    app.pods.append(fx.make_fake_pod("vip", "3", "2Gi", fx.with_priority(1000)))
    res = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    placed = {p.metadata.name for ns in res.node_status for p in ns.pods}
    assert "vip" in placed
    assert {u.pod.metadata.name for u in res.unscheduled_pods} == {"anchored"}


def test_pdb_expected_count_from_declared_replicas():
    """ADVICE r3: minAvailable 50% with 4 DECLARED replicas but only 2
    bound must allow 0 disruptions (kube resolves the percentage against
    GetExpectedPodCount — owner-declared replicas — not the healthy
    count, which would wrongly allow 1)."""
    cluster = _cluster(n=1, cpu="4")
    app = ResourceTypes()
    # a 4-replica deployment on a node that only fits 2 replicas
    app.deployments.append(fx.make_fake_deployment(
        "web", 4, "1", "1Gi",
        fx.with_pod_labels({"app": "web"}),
    ))
    app.pods.append(fx.make_fake_pod("vip", "2", "1Gi", fx.with_priority(1000)))
    app.pdbs.append(type("PDB", (), {"raw": {
        "metadata": {"namespace": "default"},
        "spec": {"minAvailable": "50%",
                 "selector": {"matchLabels": {"app": "web"}}},
    }})())
    res = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    # healthy=2, expected=4 -> desired=2 -> allowed=0: both replicas are
    # PDB-protected; with only PDB-violating victims available the ladder
    # still prefers... no alternative node exists, so eviction proceeds as
    # a last resort ONLY IF the preemptor cannot land otherwise — kube
    # does evict PDB-violating victims when every candidate violates.
    # The assertion: the budget was computed as 0, so the chosen victims
    # are counted as violations — observable as vip landing with exactly
    # one replica evicted (remove-all then reprieve keeps one).
    placed = {p.metadata.name for ns in res.node_status for p in ns.pods}
    assert "vip" in placed


@pytest.mark.parametrize("seed", [5, 21, 88, 144])
def test_preemption_fuzz_invariants(seed):
    """Randomized preemption runs (priorities + affinity + spread + gpu
    from the oracle generators) must preserve the end-state invariants:
    no node overcommitted in any resource, no host-port conflicts, every
    victim strictly lower priority than its preemptor, and every
    preemption reason names a real placed preemptor."""
    import random

    from test_k8s_oracle import random_app, random_cluster

    rng = random.Random(seed)
    cluster = random_cluster(rng, rng.randrange(3, 7))
    app = random_app(rng, rng.randrange(3, 6))
    # prioritize a random subset so preemption has work to do
    for w in app.deployments:
        if rng.random() < 0.5:
            prio = rng.choice([10, 100, 1000])
            w.template_spec.priority = prio
            w.template_raw.setdefault("spec", {})["priority"] = prio
    res = simulate(cluster, [AppResource("a", app)], enable_preemption=True)

    placed_names = {p.metadata.name for ns in res.node_status for p in ns.pods}
    by_name = {p.metadata.name: p for ns in res.node_status for p in ns.pods}
    for ns in res.node_status:
        node = ns.node
        used = {}
        ports = []
        for p in ns.pods:
            for k, v in p.resource_requests().items():
                used[k] = used.get(k, 0.0) + v
            ports.extend(
                (c.protocol, c.host_port) for c in p.host_ports()
            )
        for k, v in used.items():
            assert v <= node.allocatable.get(k, 0.0) + 1e-6, (
                f"seed={seed}: {node.metadata.name} overcommitted {k}: "
                f"{v} > {node.allocatable.get(k)}"
            )
        assert len(ports) == len(set(ports)), (
            f"seed={seed}: duplicate host ports on {node.metadata.name}"
        )
        assert len(ns.pods) <= node.allocatable.get("pods", 1e9)

    for up in res.unscheduled_pods:
        if "preempted by higher-priority pod" in up.reason:
            preemptor_name = up.reason.rsplit("/", 1)[-1]
            assert preemptor_name in placed_names, (
                f"seed={seed}: victim {up.pod.metadata.name} names missing "
                f"preemptor {preemptor_name}"
            )
            assert by_name[preemptor_name].spec.priority > up.pod.spec.priority, (
                f"seed={seed}: victim not strictly lower priority"
            )
