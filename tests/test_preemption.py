"""Opt-in preemption pass — the DefaultPreemption PostFilter the reference
registers but can never exercise (its driver deletes unschedulable pods,
simulator.go:333-342). See opensim_tpu/engine/preemption.py."""

from opensim_tpu.engine.simulator import AppResource, simulate
from opensim_tpu.models import ResourceTypes
from opensim_tpu.models import fixtures as fx


def _cluster(n=2, cpu="4", mem="8Gi"):
    rt = ResourceTypes()
    for i in range(n):
        rt.nodes.append(fx.make_fake_node(f"n{i}", cpu, mem))
    return rt


def test_high_priority_pod_lands_via_eviction():
    cluster = _cluster(n=1)
    app = ResourceTypes()
    # two low-priority pods fill the node; the late high-priority pod evicts one
    app.pods.append(fx.make_fake_pod("low-a", "2", "2Gi", fx.with_priority(10)))
    app.pods.append(fx.make_fake_pod("low-b", "2", "2Gi", fx.with_priority(20)))
    app.pods.append(fx.make_fake_pod("vip", "2", "2Gi", fx.with_priority(1000)))

    res_off = simulate(cluster, [AppResource("a", app)])
    assert {u.pod.metadata.name for u in res_off.unscheduled_pods} == {"vip"}

    res_on = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    placed = {p.metadata.name for ns in res_on.node_status for p in ns.pods}
    assert "vip" in placed
    # the LOWEST-priority victim is chosen
    assert {u.pod.metadata.name for u in res_on.unscheduled_pods} == {"low-a"}
    assert "preempted by higher-priority pod" in res_on.unscheduled_pods[0].reason
    assert "vip" in res_on.unscheduled_pods[0].reason


def test_preemption_respects_priority_order_and_caps():
    cluster = _cluster(n=1)
    app = ResourceTypes()
    # equal-priority pod cannot preempt (victims must be strictly lower)
    app.pods.append(fx.make_fake_pod("peer-a", "3", "2Gi", fx.with_priority(50)))
    app.pods.append(fx.make_fake_pod("peer-b", "3", "2Gi", fx.with_priority(50)))
    res = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    assert len(res.unscheduled_pods) == 1  # no eviction among equals

    # zero-priority unschedulable pods never preempt
    app2 = ResourceTypes()
    app2.pods.append(fx.make_fake_pod("filler", "3", "2Gi", fx.with_priority(5)))
    app2.pods.append(fx.make_fake_pod("plain", "3", "2Gi"))
    res2 = simulate(cluster, [AppResource("a", app2)], enable_preemption=True)
    assert {u.pod.metadata.name for u in res2.unscheduled_pods} == {"plain"}


def test_preemption_takes_lowest_priority_victims_first():
    cluster = _cluster(n=1, cpu="6")
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("low-a", "2", "1Gi", fx.with_priority(10)))
    app.pods.append(fx.make_fake_pod("low-b", "2", "1Gi", fx.with_priority(20)))
    app.pods.append(fx.make_fake_pod("mid", "2", "1Gi", fx.with_priority(50)))
    app.pods.append(fx.make_fake_pod("vip", "4", "2Gi", fx.with_priority(100)))
    res = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    placed = {p.metadata.name for ns in res.node_status for p in ns.pods}
    # vip frees 4 cpu by evicting the two LOWEST-priority pods; mid survives
    assert "vip" in placed and "mid" in placed
    assert {u.pod.metadata.name for u in res.unscheduled_pods} == {"low-a", "low-b"}


def test_forced_pods_are_never_victims():
    cluster = _cluster(n=1)
    cluster.pods.append(fx.make_fake_pod("resident", "3", "4Gi", fx.with_priority(1), fx.with_node_name("n0")))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("vip", "3", "4Gi", fx.with_priority(100)))
    res = simulate(cluster, [AppResource("a", app)], enable_preemption=True)
    # the pre-bound resident stays; vip remains unscheduled with a kube reason
    assert {u.pod.metadata.name for u in res.unscheduled_pods} == {"vip"}
    assert "Insufficient" in res.unscheduled_pods[0].reason
