"""Integration test mirroring the reference's ``pkg/simulator/core_test.go``:
build a 4-node cluster + base pods + cluster workloads from fixtures, run an
app with every workload kind through the real ``simulate()``, and assert
structurally (pod counts per workload, zero unschedulable) — never exact
placement, which is tie-break dependent (core_test.go:364-591 checkResult)."""

from collections import Counter

from opensim_tpu.engine.simulator import AppResource, simulate
from opensim_tpu.models import ANNO_WORKLOAD_KIND, ANNO_WORKLOAD_NAME, ResourceTypes
from opensim_tpu.models import fixtures as fx
from opensim_tpu.models import selectors
from opensim_tpu.models.expand import _daemon_pod_for_node


MASTER_LABELS = {
    "beta.kubernetes.io/arch": "amd64",
    "beta.kubernetes.io/os": "linux",
    "kubernetes.io/os": "linux",
    "node-role.kubernetes.io/master": "",
}
WORKER_LABELS = {
    "beta.kubernetes.io/os": "linux",
    "kubernetes.io/os": "linux",
    "node-role.kubernetes.io/worker": "",
}


def build_cluster() -> ResourceTypes:
    rt = ResourceTypes()
    rt.nodes.append(
        fx.make_fake_node(
            "master-1",
            "8",
            "16Gi",
            "110",
            fx.with_labels(MASTER_LABELS),
            fx.with_taints([{"key": "node-role.kubernetes.io/master", "effect": "NoSchedule"}]),
            fx.with_node_local_storage(
                vgs=[
                    {"name": "yoda-pool0", "capacity": 107374182400},
                    {"name": "yoda-pool1", "capacity": 107374182400},
                ],
                devices=[{"device": "/dev/vdd", "capacity": 107374182400, "mediaType": "hdd"}],
            ),
        )
    )
    rt.nodes.append(fx.make_fake_node("master-2", "8", "16Gi", "110", fx.with_labels(MASTER_LABELS)))
    rt.nodes.append(fx.make_fake_node("master-3", "8", "16Gi", "110", fx.with_labels(MASTER_LABELS)))
    rt.nodes.append(
        fx.make_fake_node(
            "worker-1",
            "8",
            "16Gi",
            "110",
            fx.with_labels(WORKER_LABELS),
            fx.with_node_local_storage(
                vgs=[{"name": "yoda-pool0", "capacity": 107374182400}],
                devices=[{"device": "/dev/vdd", "capacity": 107374182400, "mediaType": "hdd"}],
            ),
        )
    )
    # base pods pinned to master-1 (pre-bound — bypass scheduling but consume
    # resources, core_test.go:138-152)
    for name, cpu in [
        ("etcd-master-1", "100m"),
        ("kube-apiserver-master-1", "250m"),
        ("kube-controller-manager-master-1", "200m"),
        ("kube-scheduler-master-1", "100m"),
    ]:
        rt.pods.append(
            fx.make_fake_pod(name, cpu, "100Mi", fx.with_namespace("kube-system"), fx.with_node_name("master-1"))
        )
    # metrics-server: node affinity to masters + zone anti-affinity (the zone
    # label doesn't exist → anti-affinity is vacuous, k8s semantics)
    rt.deployments.append(
        fx.make_fake_deployment(
            "metrics-server",
            1,
            "1",
            "500Mi",
            fx.with_namespace("kube-system"),
            fx.with_pod_labels({"k8s-app": "metrics-server"}),
            fx.with_affinity(
                {
                    "nodeAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": {
                            "nodeSelectorTerms": [
                                {"matchExpressions": [{"key": "node-role.kubernetes.io/master", "operator": "Exists"}]}
                            ]
                        }
                    },
                    "podAntiAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {
                                "labelSelector": {"matchLabels": {"k8s-app": "metrics-server"}},
                                "topologyKey": "failure-domain.beta.kubernetes.io/zone",
                            }
                        ]
                    },
                }
            ),
            fx.with_tolerations([{"key": "node-role.kubernetes.io/master", "operator": "Exists", "effect": "NoSchedule"}]),
        )
    )
    rt.daemon_sets.append(
        fx.make_fake_daemon_set(
            "kube-proxy-master",
            "100m",
            "64Mi",
            fx.with_namespace("kube-system"),
            fx.with_tolerations([{"operator": "Exists"}]),
            fx.with_node_selector({"node-role.kubernetes.io/master": ""}),
        )
    )
    rt.daemon_sets.append(
        fx.make_fake_daemon_set(
            "kube-proxy-worker",
            "100m",
            "64Mi",
            fx.with_namespace("kube-system"),
            fx.with_tolerations([{"operator": "Exists"}]),
            fx.with_node_selector({"node-role.kubernetes.io/worker": ""}),
        )
    )
    rt.daemon_sets.append(
        fx.make_fake_daemon_set(
            "coredns",
            "100m",
            "70Mi",
            fx.with_namespace("kube-system"),
            fx.with_affinity(
                {
                    "nodeAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": {
                            "nodeSelectorTerms": [
                                {"matchExpressions": [{"key": "node-role.kubernetes.io/master", "operator": "Exists"}]}
                            ]
                        }
                    }
                }
            ),
            fx.with_tolerations([{"key": "node-role.kubernetes.io/master", "effect": "NoSchedule"}]),
            fx.with_node_selector({"beta.kubernetes.io/os": "linux"}),
        )
    )
    return rt


def build_app() -> ResourceTypes:
    rt = ResourceTypes()
    rt.deployments.append(
        fx.make_fake_deployment(
            "app-deploy",
            4,
            "1",
            "1Gi",
            fx.with_tolerations([{"key": "node-role.kubernetes.io/master", "operator": "Exists", "effect": "NoSchedule"}]),
        )
    )
    rt.daemon_sets.append(
        fx.make_fake_daemon_set("app-agent", "100m", "128Mi", fx.with_tolerations([{"operator": "Exists"}]))
    )
    rt.jobs.append(fx.make_fake_job("app-job", 2, "500m", "256Mi"))
    rt.pods.append(fx.make_fake_pod("app-pod", "100m", "128Mi"))
    sts = fx.make_fake_stateful_set(
        "app-db",
        2,
        "1",
        "2Gi",
        fx.with_affinity(
            {
                "podAntiAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [
                        {
                            "labelSelector": {"matchLabels": {"app": "app-db"}},
                            "topologyKey": "kubernetes.io/hostname",
                        }
                    ]
                }
            }
        ),
    )
    rt.stateful_sets.append(sts)
    rt.replica_sets.append(fx.make_fake_replica_set("app-rs", 2, "200m", "256Mi"))
    return rt


def test_simulate_end_to_end():
    cluster = build_cluster()
    app = build_app()
    res = simulate(cluster, [AppResource("simple", app)])

    reasons = [(u.pod.metadata.name, u.reason) for u in res.unscheduled_pods]
    assert not reasons, f"unexpected unschedulable pods: {reasons}"

    all_pods = [p for ns in res.node_status for p in ns.pods]
    by_workload = Counter(
        (p.metadata.annotations.get(ANNO_WORKLOAD_KIND, "bare"), p.metadata.annotations.get(ANNO_WORKLOAD_NAME, p.metadata.name))
        for p in all_pods
    )
    # daemonset expectations recomputed via node_should_run_pod, mirroring
    # checkResult (core_test.go:472-479)
    for ds in cluster.daemon_sets + app.daemon_sets:
        expected = sum(
            1
            for node in cluster.nodes
            if selectors.node_should_run_pod(node, _daemon_pod_for_node(ds, node.metadata.name))
        )
        assert by_workload[("DaemonSet", ds.metadata.name)] == expected, ds.metadata.name

    # deployment pods are attributed through their generated ReplicaSet name
    # (checkResult, core_test.go:519-577)
    def count_prefix(kind: str, prefix: str) -> int:
        return sum(c for (k, n), c in by_workload.items() if k == kind and n.startswith(prefix))

    assert count_prefix("ReplicaSet", "metrics-server-") == 1
    assert count_prefix("ReplicaSet", "app-deploy-") == 4
    assert by_workload[("Job", "app-job")] == 2
    assert by_workload[("StatefulSet", "app-db")] == 2
    assert by_workload[("ReplicaSet", "app-rs")] == 2
    assert by_workload[("bare", "app-pod")] == 1

    # metrics-server must land on a master (node affinity)
    ms_pod = [
        p
        for p in all_pods
        if (p.metadata.annotations.get(ANNO_WORKLOAD_NAME) or "").startswith("metrics-server-")
    ][0]
    assert ms_pod.spec.node_name.startswith("master")

    # anti-affinity: the two db pods are on distinct nodes
    db_nodes = {p.spec.node_name for p in all_pods if p.metadata.annotations.get(ANNO_WORKLOAD_NAME) == "app-db"}
    assert len(db_nodes) == 2

    # pre-bound pods stayed on master-1 and consumed its resources
    m1 = res.pods_on("master-1")
    assert any(p.metadata.name == "etcd-master-1" for p in m1)


def test_unschedulable_reports_reason():
    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n1", "2", "4Gi"))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("fat-pod", "16", "1Gi"))
    app.pods.append(fx.make_fake_pod("picky-pod", "100m", "128Mi", fx.with_node_selector({"disk": "ssd"})))
    res = simulate(cluster, [AppResource("a", app)])
    assert len(res.unscheduled_pods) == 2
    reasons = {u.pod.metadata.name: u.reason for u in res.unscheduled_pods}
    assert "Insufficient cpu" in reasons["fat-pod"]
    assert "node affinity" in reasons["picky-pod"]
    assert reasons["fat-pod"].startswith("0/1 nodes are available")


def test_taints_block_and_tolerations_admit():
    cluster = ResourceTypes()
    cluster.nodes.append(
        fx.make_fake_node("tainted", "8", "16Gi", "110", fx.with_taints([{"key": "dedicated", "value": "gpu", "effect": "NoSchedule"}]))
    )
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("no-tol", "100m", "128Mi"))
    app.pods.append(
        fx.make_fake_pod("with-tol", "100m", "128Mi", fx.with_tolerations([{"key": "dedicated", "operator": "Equal", "value": "gpu", "effect": "NoSchedule"}]))
    )
    res = simulate(cluster, [AppResource("a", app)])
    names = {u.pod.metadata.name for u in res.unscheduled_pods}
    assert names == {"no-tol"}
    assert "taint" in res.unscheduled_pods[0].reason


def test_host_port_conflict():
    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n1", "8", "16Gi"))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("p1", "100m", "128Mi", fx.with_host_ports([8080])))
    app.pods.append(fx.make_fake_pod("p2", "100m", "128Mi", fx.with_host_ports([8080])))
    res = simulate(cluster, [AppResource("a", app)])
    assert len(res.unscheduled_pods) == 1
    assert "free ports" in res.unscheduled_pods[0].reason


def test_topology_spread_hard_constraint():
    cluster = ResourceTypes()
    for i in range(2):
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
    app = ResourceTypes()
    deploy = fx.make_fake_deployment(
        "spread",
        3,
        "100m",
        "128Mi",
        fx.with_topology_spread(
            [
                {
                    "maxSkew": 1,
                    "topologyKey": "kubernetes.io/hostname",
                    "whenUnsatisfiable": "DoNotSchedule",
                    "labelSelector": {"matchLabels": {"app": "spread"}},
                }
            ]
        ),
    )
    app.deployments.append(deploy)
    res = simulate(cluster, [AppResource("a", app)])
    # 3 pods over 2 nodes with maxSkew 1 → 2+1 placement, all feasible
    assert not res.unscheduled_pods
    per_node = sorted(len(ns.pods) for ns in res.node_status)
    assert per_node == [1, 2]


def test_pod_affinity_colocates():
    cluster = ResourceTypes()
    for i in range(3):
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("anchor", "100m", "128Mi", fx.with_labels({"role": "anchor"})))
    app.pods.append(
        fx.make_fake_pod(
            "follower",
            "100m",
            "128Mi",
            fx.with_affinity(
                {
                    "podAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {"labelSelector": {"matchLabels": {"role": "anchor"}}, "topologyKey": "kubernetes.io/hostname"}
                        ]
                    }
                }
            ),
        )
    )
    res = simulate(cluster, [AppResource("a", app)])
    assert not res.unscheduled_pods
    nodes = {}
    for ns in res.node_status:
        for p in ns.pods:
            nodes[p.metadata.name] = ns.node.metadata.name
    assert nodes["anchor"] == nodes["follower"]


def test_local_device_volumes_match_per_volume():
    """Open-local exclusive devices: a 10Gi + 100Gi SSD pair fits devices of
    20Gi + 120Gi (one device per volume, common.go:290-349) — the old
    count × max-size encoding wrongly demanded two ≥100Gi devices. Two
    100Gi volumes still fail on that node."""
    G = 1024 ** 3

    def node():
        return fx.make_fake_node(
            "s1", "16", "32Gi", "110",
            fx.with_node_local_storage(
                devices=[
                    {"device": "/dev/vdb", "capacity": 20 * G, "mediaType": "ssd"},
                    {"device": "/dev/vdc", "capacity": 120 * G, "mediaType": "ssd"},
                ]
            ),
        )

    def run(sizes):
        cluster = ResourceTypes()
        cluster.nodes.append(node())
        sts = fx.make_fake_stateful_set("db", 1, "500m", "1Gi")
        sts.volume_claim_templates = [
            {"metadata": {"name": f"v{i}"},
             "spec": {"storageClassName": "open-local-device-ssd",
                      "resources": {"requests": {"storage": s}}}}
            for i, s in enumerate(sizes)
        ]
        app = ResourceTypes()
        app.stateful_sets.append(sts)
        return simulate(cluster, [AppResource("a", app)])

    assert not run(["10Gi", "100Gi"]).unscheduled_pods
    res = run(["100Gi", "100Gi"])
    assert len(res.unscheduled_pods) == 1
    assert "local storage" in res.unscheduled_pods[0].reason


def test_host_port_wildcard_overlaps_specific_ip():
    """NodePorts: hostIP 0.0.0.0/"" overlaps every specific address on the
    same port/protocol (nodeports.go ckConflict), while two distinct
    specific addresses coexist."""
    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n1", "8", "16Gi"))
    cluster.pods.append(
        fx.make_fake_pod(
            "holder", "100m", "128Mi", fx.with_node_name("n1"),
            fx.with_host_port_specs([{"hostPort": 8080, "containerPort": 8080, "protocol": "TCP", "hostIP": "10.0.0.1"}]),
        )
    )
    app = ResourceTypes()
    app.pods.append(
        fx.make_fake_pod(
            "wild", "100m", "128Mi",
            fx.with_host_port_specs([{"hostPort": 8080, "containerPort": 8080, "protocol": "TCP"}]),
        )
    )
    app.pods.append(
        fx.make_fake_pod(
            "other-ip", "100m", "128Mi",
            fx.with_host_port_specs([{"hostPort": 8080, "containerPort": 8080, "protocol": "TCP", "hostIP": "10.0.0.2"}]),
        )
    )
    res = simulate(cluster, [AppResource("a", app)])
    names = {u.pod.metadata.name for u in res.unscheduled_pods}
    # wildcard conflicts with the specific-IP holder; a different specific IP does not
    assert names == {"wild"}
    assert "free ports" in res.unscheduled_pods[0].reason


def test_host_port_specific_conflicts_with_wildcard_holder():
    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n1", "8", "16Gi"))
    cluster.pods.append(
        fx.make_fake_pod(
            "holder", "100m", "128Mi", fx.with_node_name("n1"),
            fx.with_host_port_specs([{"hostPort": 9090, "containerPort": 9090, "protocol": "TCP", "hostIP": "0.0.0.0"}]),
        )
    )
    app = ResourceTypes()
    app.pods.append(
        fx.make_fake_pod(
            "specific", "100m", "128Mi",
            fx.with_host_port_specs([{"hostPort": 9090, "containerPort": 9090, "protocol": "TCP", "hostIP": "10.0.0.9"}]),
        )
    )
    res = simulate(cluster, [AppResource("a", app)])
    assert len(res.unscheduled_pods) == 1


def _two_term_affinity():
    return {
        "podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {"labelSelector": {"matchLabels": {"role": "db"}}, "topologyKey": "kubernetes.io/hostname"},
                {"labelSelector": {"matchLabels": {"tier": "hot"}}, "topologyKey": "kubernetes.io/hostname"},
            ]
        }
    }


def test_multi_term_affinity_needs_one_pod_matching_all_terms():
    """k8s counts only existing pods that match ALL of the incoming pod's
    required affinity terms (filtering.go:113-127): two pods each satisfying
    one term do NOT make a node feasible."""
    cluster = ResourceTypes()
    cluster.nodes += [fx.make_fake_node("n1", "8", "16Gi"), fx.make_fake_node("n2", "8", "16Gi")]
    cluster.pods += [
        fx.make_fake_pod("db-1", "100m", "128Mi", fx.with_labels({"role": "db"}), fx.with_node_name("n1")),
        fx.make_fake_pod("hot-1", "100m", "128Mi", fx.with_labels({"tier": "hot"}), fx.with_node_name("n1")),
    ]
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("seeker", "100m", "128Mi", fx.with_affinity(_two_term_affinity())))
    res = simulate(cluster, [AppResource("a", app)])
    assert len(res.unscheduled_pods) == 1
    assert res.unscheduled_pods[0].pod.metadata.name == "seeker"


def test_multi_term_affinity_one_pod_matches_all():
    """A single existing pod carrying every term's labels makes its node
    (and only its node) feasible."""
    cluster = ResourceTypes()
    cluster.nodes += [fx.make_fake_node("n1", "8", "16Gi"), fx.make_fake_node("n2", "8", "16Gi")]
    cluster.pods.append(
        fx.make_fake_pod(
            "both-1", "100m", "128Mi", fx.with_labels({"role": "db", "tier": "hot"}), fx.with_node_name("n2")
        )
    )
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("seeker", "100m", "128Mi", fx.with_affinity(_two_term_affinity())))
    res = simulate(cluster, [AppResource("a", app)])
    assert not res.unscheduled_pods
    placed = {p.metadata.name: ns.node.metadata.name for ns in res.node_status for p in ns.pods}
    assert placed["seeker"] == "n2"


def test_multi_term_affinity_bootstrap_requires_full_self_match():
    """First-pod bootstrap (filtering.go:361-369): the global count map must
    be empty AND the pod must match ALL its own terms."""
    def run(labels):
        cluster = ResourceTypes()
        cluster.nodes += [fx.make_fake_node("n1", "8", "16Gi")]
        app = ResourceTypes()
        app.pods.append(
            fx.make_fake_pod(
                "self", "100m", "128Mi", fx.with_labels(labels), fx.with_affinity(_two_term_affinity())
            )
        )
        return simulate(cluster, [AppResource("a", app)])

    # matches both of its own terms → bootstraps onto any labeled node
    assert not run({"role": "db", "tier": "hot"}).unscheduled_pods
    # matches only one of its own terms → no bootstrap, unschedulable
    assert len(run({"role": "db"}).unscheduled_pods) == 1


def test_multi_namespace_anti_affinity():
    """A pod-anti-affinity term listing several namespaces must match pods
    in any of them (previously only the first namespace counted)."""
    cluster = ResourceTypes()
    for i in range(2):
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
    app = ResourceTypes()
    app.pods.append(
        fx.make_fake_pod("occupant", "100m", "128Mi", fx.with_namespace("ns-b"), fx.with_labels({"role": "x"}))
    )
    app.pods.append(
        fx.make_fake_pod(
            "avoider",
            "100m",
            "128Mi",
            fx.with_namespace("ns-a"),
            fx.with_affinity(
                {
                    "podAntiAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {
                                "labelSelector": {"matchLabels": {"role": "x"}},
                                "namespaces": ["ns-c", "ns-b"],
                                "topologyKey": "kubernetes.io/hostname",
                            }
                        ]
                    }
                }
            ),
        )
    )
    res = simulate(cluster, [AppResource("a", app)])
    assert not res.unscheduled_pods
    nodes = {p.metadata.name: ns.node.metadata.name for ns in res.node_status for p in ns.pods}
    # ns-b is the SECOND listed namespace; the avoider must still dodge it
    assert nodes["avoider"] != nodes["occupant"]


def test_10k_node_cluster_encodes_and_schedules():
    """Scale-point guard (BASELINE 2x headline shape): a 10k-node cluster
    encodes and schedules without shape/memory cliffs — the node axis pads
    to 128-lane buckets (10000 -> 10240) and placements stay structural."""
    from opensim_tpu.engine.simulator import AppResource, simulate
    from opensim_tpu.models import ResourceTypes, fixtures as fx

    rt = ResourceTypes()
    zones = [f"z{z}" for z in range(4)]
    for i in range(10_000):
        rt.nodes.append(fx.make_fake_node(
            f"n{i:05d}", "64", "256Gi", "256",
            fx.with_labels({"topology.kubernetes.io/zone": zones[i % 4]}),
        ))
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("w", 500, "500m", "1Gi"))
    res = simulate(rt, [AppResource("a", app)])
    assert not res.unscheduled_pods
    assert sum(len(ns.pods) for ns in res.node_status) == 500
