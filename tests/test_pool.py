"""server/pool.py process mode (ISSUE 15 satellite): the fork+probe path
has been opt-in and untested since PR 8 — these are its direct gates:

- the probe proves a forked worker executes and answers (and a failing
  probe falls back to threads, never a broken server);
- unpicklable tasks transparently run on the thread executor;
- COW arena inheritance actually serves a request: a worker forked AFTER
  the parent built its warm ``Prepared`` schedules over the inherited
  arenas and returns placements identical to the parent's.
"""

import multiprocessing

import numpy as np
import pytest

from opensim_tpu.engine.simulator import AppResource, prepare
from opensim_tpu.models import ResourceTypes, fixtures as fx
from opensim_tpu.server import pool as pool_mod
from opensim_tpu.server.pool import WorkerPool

# module-level state the forked workers inherit copy-on-write; built
# lazily so importing this module stays cheap
_PREP = None


def _build_prep():
    global _PREP
    if _PREP is None:
        rt = ResourceTypes()
        for i in range(4):
            rt.nodes.append(fx.make_fake_node(f"n{i:02d}", "16", "64Gi"))
        rt.pods.append(
            fx.make_fake_pod("seed", "100m", "128Mi", fx.with_node_name("n00"))
        )
        app = ResourceTypes()
        app.add(fx.make_fake_deployment("cow", 3, "500m", "1Gi"))
        _PREP = prepare(rt, [AppResource("deploy", app)])
    return _PREP


def _cow_schedule() -> list:
    """Runs INSIDE a forked worker: schedule the pod stream over the
    parent's arenas through the C++ engine (ctypes + numpy — no XLA
    dispatch in the child). Module-level so it pickles by reference."""
    from opensim_tpu.engine import nativepath

    prep = _PREP  # inherited COW from the parent — never rebuilt here
    assert prep is not None, "fork did not inherit the parent's Prepared"
    out = nativepath.schedule(prep, np.ones((len(prep.ordered),), dtype=bool))
    return [int(c) for c in np.asarray(out.chosen)]


def _probe_ok() -> str:
    return "alive"


def _fork_available() -> bool:
    return "fork" in multiprocessing.get_all_start_methods()


def _process_pool() -> WorkerPool:
    if not _fork_available():  # pragma: no cover - non-posix
        pytest.skip("fork start method unavailable")
    p = WorkerPool(workers=2, mode="process")
    if p.mode != "process":  # pragma: no cover - wedged platform
        p.shutdown()
        pytest.skip("process pool probe failed on this platform")
    return p


def test_probe_brings_up_process_mode_and_executes():
    p = _process_pool()
    try:
        assert p.submit(_probe_ok).result(timeout=60.0) == "alive"
    finally:
        p.shutdown()


def test_no_fork_platform_falls_back_to_threads(monkeypatch):
    monkeypatch.setattr(multiprocessing, "get_all_start_methods", lambda: ["spawn"])
    p = WorkerPool(workers=2, mode="process")
    try:
        assert p.mode == "thread"
        assert p.submit(_probe_ok).result(timeout=30.0) == "alive"
    finally:
        p.shutdown()


def test_probe_failure_falls_back_to_threads(monkeypatch):
    """A forked child that answers the probe WRONG (stand-in for a wedged
    runtime) must demote the pool to threads at startup, not surface on
    the first real request."""
    if not _fork_available():  # pragma: no cover - non-posix
        pytest.skip("fork start method unavailable")
    # fork children inherit the patched module COW, so the probe really
    # executes the broken version in the child
    monkeypatch.setattr(pool_mod, "_probe", lambda: -1)
    p = WorkerPool(workers=2, mode="process")
    try:
        assert p.mode == "thread"
    finally:
        p.shutdown()


def test_unpicklable_task_runs_on_threads():
    p = _process_pool()
    try:
        captured = []  # closure: unpicklable by reference

        def task():
            captured.append(1)
            return "threads"

        assert p.submit(task).result(timeout=30.0) == "threads"
        assert captured == [1]  # ran in THIS process (thread fallback)
        assert p._warned_unpicklable
    finally:
        p.shutdown()


def test_cow_arena_inheritance_serves_a_request():
    """The point of fork mode: a worker forked after the parent's warm
    prepare schedules over the inherited arenas — no re-prepare, and the
    placements match the parent's bit for bit."""
    from opensim_tpu import native
    from opensim_tpu.engine import nativepath

    if not native.available():  # pragma: no cover - no C++ toolchain
        pytest.skip("C++ engine unavailable")
    prep = _build_prep()
    if nativepath.why_not(prep, None, ()) is not None:
        pytest.skip("stream outside the C++ engine envelope")
    expected = [
        int(c)
        for c in np.asarray(
            nativepath.schedule(prep, np.ones((len(prep.ordered),), dtype=bool)).chosen
        )
    ]
    # the pool is created AFTER the prep: workers inherit it copy-on-write
    p = _process_pool()
    try:
        got = p.submit(_cow_schedule).result(timeout=120.0)
        assert got == expected
    finally:
        p.shutdown()
