"""Tracing and checkpoint/resume tests."""

import logging

import numpy as np

from opensim_tpu.encoding.state import ClusterEncoder
from opensim_tpu.models import ResourceTypes, fixtures as fx
from opensim_tpu.utils.checkpoint import load_state, save_state
from opensim_tpu.utils.trace import Trace


def test_trace_logs_only_over_threshold(caplog):
    with caplog.at_level(logging.WARNING, logger="opensim_tpu.trace"):
        with Trace("fast", threshold_s=10.0) as tr:
            tr.step("noop")
        assert not caplog.records
        with Trace("slow", threshold_s=0.0) as tr:
            tr.step("one")
        assert any("slow" in r.message for r in caplog.records)


def test_checkpoint_roundtrip(tmp_path):
    enc = ClusterEncoder()
    enc.add_nodes([fx.make_fake_node("n0"), fx.make_fake_node("n1")])
    enc.add_pod(fx.make_fake_pod("p0", "1", "1Gi"))
    ec, st, _meta = enc.build()
    path = str(tmp_path / "ckpt.npz")
    save_state(path, ec, st, extra={"round": 3})
    ec2, st2, extra = load_state(path)
    assert extra == {"round": 3}
    for a, b in zip(ec, ec2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(st, st2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # resuming the scan from a checkpoint gives identical results
    from opensim_tpu.engine.scheduler import schedule_pods, to_device

    tmpl = np.zeros(4, np.int32)
    valid = np.ones(4, bool)
    forced = np.zeros(4, bool)
    ecd, std = to_device(ec, st)
    ecd2, std2 = to_device(ec2, st2)
    out1 = schedule_pods(ecd, std, tmpl, valid, forced)
    out2 = schedule_pods(ecd2, std2, tmpl, valid, forced)
    np.testing.assert_array_equal(np.asarray(out1.chosen), np.asarray(out2.chosen))


def test_checkpoint_backfills_old_archives(tmp_path):
    """The NOTES.md invariant, previously untested: loading an archive
    written before EncodedCluster grew ``gc_mask`` and ``log_sizes`` must
    backfill both — gc_mask all-static (exactly the saved behavior) and
    log_sizes bit-identical to the shared table the encoder would build."""
    from opensim_tpu.encoding.dtypes import log_size_table

    enc = ClusterEncoder()
    enc.add_nodes([fx.make_fake_node("n0"), fx.make_fake_node("n1")])
    enc.add_pod(fx.make_fake_pod("p0", "1", "1Gi"))
    ec, st, _meta = enc.build()
    path = str(tmp_path / "old.npz")
    save_state(path, ec, st)

    # rewrite the archive WITHOUT the two newer fields, as a pre-gc_mask
    # checkpoint would have been written
    with np.load(path) as data:
        stripped = {
            k: data[k] for k in data.files if k not in ("ec_gc_mask", "ec_log_sizes")
        }
    np.savez_compressed(path, **stripped)

    ec2, st2, _extra = load_state(path)
    np.testing.assert_array_equal(
        np.asarray(ec2.gc_mask), np.zeros((np.asarray(ec.alloc).shape[1],), dtype=bool)
    )
    np.testing.assert_array_equal(
        np.asarray(ec2.log_sizes), log_size_table(np.asarray(ec.alloc).shape[0])
    )
    # every other field survives untouched
    for name, a in ec._asdict().items():
        if name in ("gc_mask", "log_sizes"):
            continue
        np.testing.assert_array_equal(np.asarray(a), np.asarray(getattr(ec2, name)))

    # and the resumed state still schedules identically to the original
    from opensim_tpu.engine.scheduler import schedule_pods, to_device

    tmpl = np.zeros(2, np.int32)
    valid = np.ones(2, bool)
    forced = np.zeros(2, bool)
    out1 = schedule_pods(*to_device(ec, st), tmpl, valid, forced)
    out2 = schedule_pods(*to_device(ec2, st2), tmpl, valid, forced)
    np.testing.assert_array_equal(np.asarray(out1.chosen), np.asarray(out2.chosen))


def test_progress_spinner_and_bar(monkeypatch):
    """pterm-parity progress (simulator.go:311-321): the spinner leaves a
    final tally line and stays silent when disabled."""
    import io
    import time as _time

    from opensim_tpu.utils import progress

    monkeypatch.delenv("OPENSIM_NO_PROGRESS", raising=False)

    buf = io.StringIO()
    with progress.Spinner("work", stream=buf, enabled=True):
        _time.sleep(0.25)
    text = buf.getvalue()
    assert "work" in text and "✓" in text

    silent = io.StringIO()
    with progress.Spinner("quiet", stream=silent, enabled=False):
        pass
    assert silent.getvalue() == ""

