"""Test configuration: force an 8-device virtual CPU mesh so multi-chip
sharding paths run without TPU hardware.

Note: the axon environment's sitecustomize overrides the JAX_PLATFORMS env
var, so the platform must be forced through jax.config after import."""

import os

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/opensim-jit-cache")

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# OPENSIM_TEST_BACKEND=tpu opts out of the CPU forcing so the fastpath /
# kernel-parity tests can run through compiled Mosaic on real hardware.
if os.environ.get("OPENSIM_TEST_BACKEND") != "tpu":
    jax.config.update("jax_platforms", "cpu")
