"""Live-twin watch consumer coverage (ISSUE 6): event-sourced twin
semantics (rv-monotonic application, tombstones, admissibility
transitions), O(changes) prep-cache maintenance, and the supervised failure
surface — disconnect/reconnect, 410 Gone relist-and-rebase, staleness
degradation with stale-tagged responses, lost-event drift caught by
anti-entropy — all driven end-to-end against the canned stub apiserver
(``server/stubapi.py``) over the stdlib REST watch source. Part of
``make chaos``."""

import json
import threading
import time
import urllib.request
from contextlib import contextmanager

import pytest

from opensim_tpu.engine.prepcache import fingerprint_cluster
from opensim_tpu.models import ResourceTypes, fixtures as fx
from opensim_tpu.models.objects import Pod
from opensim_tpu.resilience import faults
from opensim_tpu.server import rest
from opensim_tpu.server.snapshot import _cluster_via_rest
from opensim_tpu.server.stubapi import StubApiServer
from opensim_tpu.server.watch import (
    ClusterTwin,
    GoneError,
    RestWatchSource,
    WatchSupervisor,
    watch_policy,
)

# small knobs so failure paths resolve in tens of milliseconds, not minutes
FAST = {"stale_s": 3.0, "resync_s": 0.0, "reconnects": 3, "backoff_s": 0.01}

LIST_PATHS = (
    "/api/v1/nodes",
    "/api/v1/pods",
    "/apis/apps/v1/daemonsets",
    "/apis/policy/v1/poddisruptionbudgets",
    "/api/v1/services",
    "/apis/storage.k8s.io/v1/storageclasses",
    "/api/v1/persistentvolumeclaims",
    "/api/v1/configmaps",
)


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("OPENSIM_FAULTS", raising=False)
    faults.clear_faults()
    yield
    faults.clear_faults()


def _pod_dict(name, phase="Pending", node="", cpu="100m", rv=None):
    d = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": cpu, "memory": "64Mi"}}}]},
        "status": {"phase": phase},
    }
    if node:
        d["spec"]["nodeName"] = node
    if rv is not None:
        d["metadata"]["resourceVersion"] = str(rv)
    return d


def _wait(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _seed(stub, n_nodes=4, pods=()):
    stub.seed("/api/v1/nodes", [fx.make_fake_node(f"n{i}", "8", "16Gi").raw for i in range(n_nodes)])
    stub.seed("/api/v1/pods", list(pods))
    for p in LIST_PATHS[2:]:
        stub.seed(p, [])


@contextmanager
def _twin_server(tmp_path, policy=None, bookmark_s=0.1, pods=(), wire_server=True):
    """stub apiserver + synced supervisor (+ optionally a SimonServer whose
    prep cache the supervisor maintains)."""
    stub = StubApiServer(bookmark_interval_s=bookmark_s).start()
    _seed(stub, pods=pods)
    kc = stub.kubeconfig(tmp_path)
    pol = dict(FAST, **(policy or {}))
    sup = WatchSupervisor(
        RestWatchSource(kc, read_timeout_s=max(pol["stale_s"], 3.0)), policy=pol
    )
    server = rest.SimonServer(kubeconfig=kc, watch=sup) if wire_server else None
    if server is not None:
        sup.prep_cache = server.prep_cache
    try:
        assert sup.start(wait_s=15.0), "twin failed to sync against the stub"
        yield stub, sup, server, kc
    finally:
        sup.stop()
        stub.stop()


def _shape(resp):
    """Placement shape (pod names embed a process-global expansion counter,
    so recovery equality is shape-based — the chaos-suite idiom)."""
    return (
        sorted((e["node"], len(e["pods"])) for e in resp["nodeStatus"]),
        sorted(u["reason"] for u in resp["unscheduledPods"]),
    )


def _payload():
    return {"deployments": [fx.make_fake_deployment("web", 5, "500m", "1Gi").raw]}


# ---------------------------------------------------------------------------
# ClusterTwin unit semantics: duplicates, reordering, tombstones
# ---------------------------------------------------------------------------


def test_twin_event_application_is_rv_monotonic():
    twin = ClusterTwin()
    twin.rebase("pods", [_pod_dict("a", rv=5)])
    gen0 = twin.generation

    # duplicate delivery (same rv) is a no-op
    assert twin.apply_event("pods", "ADDED", _pod_dict("a", rv=5)) is None
    assert twin.generation == gen0
    # reordered stale MODIFIED (older rv) is a no-op
    assert twin.apply_event("pods", "MODIFIED", _pod_dict("a", rv=4)) is None
    # a genuinely newer MODIFIED applies (and needs a rebuild, not a delta)
    change = twin.apply_event("pods", "MODIFIED", _pod_dict("a", rv=9))
    assert change[0] == "rebuild"

    # new pod: delta-expressible add
    change = twin.apply_event("pods", "ADDED", _pod_dict("b", rv=10))
    assert change[0] == "pod_add" and change[1].metadata.name == "b"

    # DELETED removes + tombstones; a reordered stale MODIFIED cannot
    # resurrect the object
    change = twin.apply_event("pods", "DELETED", _pod_dict("a", rv=12))
    assert change == ("pod_del", ("default", "a"))
    assert twin.apply_event("pods", "MODIFIED", _pod_dict("a", rv=11)) is None
    assert [p.metadata.name for p in twin.materialize().pods] == ["b"]

    # duplicate DELETED is a no-op
    assert twin.apply_event("pods", "DELETED", _pod_dict("a", rv=12)) is None


def test_twin_admissibility_transition_is_a_delete():
    twin = ClusterTwin()
    twin.rebase("pods", [_pod_dict("run", phase="Running", node="n1", rv=3)])
    # Running -> Succeeded leaves the admissible set: the twin treats the
    # MODIFIED as a deletion (snapshot filter parity)
    change = twin.apply_event("pods", "MODIFIED", _pod_dict("run", phase="Succeeded", node="n1", rv=7))
    assert change == ("pod_del", ("default", "run"))
    assert twin.materialize().pods == []
    # an inadmissible ADDED for an unknown pod is a full no-op
    assert twin.apply_event("pods", "ADDED", _pod_dict("done", phase="Failed", rv=9)) is None


def test_twin_node_events():
    twin = ClusterTwin()
    twin.rebase("nodes", [fx.make_fake_node("n0", "8", "16Gi").raw])
    n1 = fx.make_fake_node("n1", "8", "16Gi").raw
    n1["metadata"]["resourceVersion"] = "20"
    change = twin.apply_event("nodes", "ADDED", n1)
    assert change[0] == "node_add" and change[1].metadata.name == "n1"
    n1b = json.loads(json.dumps(n1))
    n1b["metadata"]["resourceVersion"] = "21"
    n1b["spec"] = {"unschedulable": True}
    assert twin.apply_event("nodes", "MODIFIED", n1b)[0] == "rebuild"
    assert twin.apply_event("nodes", "DELETED", n1b)[0] == "rebuild"
    assert [n.metadata.name for n in twin.materialize().nodes] == ["n0"]


def test_twin_fingerprint_matches_equivalent_list():
    twin = ClusterTwin()
    nodes = [fx.make_fake_node(f"n{i}", "4", "8Gi").raw for i in range(3)]
    twin.rebase("nodes", nodes)
    twin.rebase("pods", [_pod_dict("a", rv=1), _pod_dict("b", rv=2)])
    twin.apply_event("pods", "ADDED", _pod_dict("c", rv=9))
    twin.apply_event("pods", "DELETED", _pod_dict("a", rv=10))

    ref = ResourceTypes()
    from opensim_tpu.models.objects import Node

    ref.nodes.extend(Node.from_dict(d) for d in nodes)
    ref.pods.append(Pod.from_dict(_pod_dict("b", rv=2)))
    ref.pods.append(Pod.from_dict(_pod_dict("c", rv=9)))
    assert twin.fingerprint() == fingerprint_cluster(ref)


def test_reconcile_never_reverts_twin_ahead_of_listing():
    """Anti-entropy races the event streams: objects the twin legitimately
    advanced past the listing (newer rv, created-after-list, deleted-after-
    list) are NOT drift and must not be reverted — only genuinely lost
    events count and get repaired."""
    twin = ClusterTwin()
    twin.rebase("pods", [_pod_dict("stay", rv=5), _pod_dict("victim", rv=6)])

    # twin moves ahead of a listing taken at list_rv=10: a MODIFIED to
    # rv=12, a brand-new pod at rv=13, and a deletion at rv=14
    assert twin.apply_event("pods", "MODIFIED", _pod_dict("stay", rv=12))
    assert twin.apply_event("pods", "ADDED", _pod_dict("young", rv=13))
    assert twin.apply_event("pods", "DELETED", _pod_dict("victim", rv=14))

    listing = {
        "pods": (
            [_pod_dict("stay", rv=5), _pod_dict("victim", rv=6), _pod_dict("lost", rv=9)],
            "10",
        )
    }
    drift = twin.reconcile(listing)
    # exactly ONE genuine drift: the 'lost' ADDED the stream never delivered
    assert drift == 1
    assert {p.metadata.name for p in twin.materialize().pods} == {"stay", "young", "lost"}
    # and the ahead-of-list state survived untouched
    stay = next(p for p in twin.materialize().pods if p.metadata.name == "stay")
    assert stay.raw["metadata"]["resourceVersion"] == "12"

    # a converged twin reconciles to zero against its own listing
    again = {
        "pods": (
            [_pod_dict("stay", rv=12), _pod_dict("young", rv=13), _pod_dict("lost", rv=9)],
            "15",
        )
    }
    assert twin.reconcile(again) == 0


def test_reconcile_repairs_lost_delete_and_lost_modify():
    twin = ClusterTwin()
    twin.rebase("pods", [_pod_dict("phantom", rv=3), _pod_dict("behind", rv=4)])
    listing = {"pods": ([_pod_dict("behind", rv=8)], "9")}
    drift = twin.reconcile(listing)
    assert drift == 2  # phantom removed (lost DELETED) + behind replaced
    pods = twin.materialize().pods
    assert [p.metadata.name for p in pods] == ["behind"]
    assert pods[0].raw["metadata"]["resourceVersion"] == "8"


# ---------------------------------------------------------------------------
# prep-cache delta: placements bit-equal to a fresh prepare
# ---------------------------------------------------------------------------


def test_twin_pod_delta_placements_bit_equal_to_fresh_prepare():
    """A pod ADDED + a pod DELETED expressed as a base-entry delta schedule
    byte-identically to a fresh full prepare of the re-listed cluster —
    cluster pod names are stable, so equality is by name, not shape."""
    from opensim_tpu.engine import prepcache
    from opensim_tpu.engine.simulator import prepare, simulate

    def cluster(with_new=False, without_dead=False):
        rt = ResourceTypes()
        for i in range(4):
            rt.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
        if not without_dead:
            rt.pods.append(Pod.from_dict(_pod_dict("dead", phase="Running", node="n0", cpu="300m")))
        rt.pods.append(Pod.from_dict(_pod_dict("keep", phase="Pending", cpu="200m")))
        if with_new:
            rt.pods.append(Pod.from_dict(_pod_dict("new-a", cpu="450m")))
            rt.pods.append(Pod.from_dict(_pod_dict("new-b", cpu="150m")))
        return rt

    base_cluster = cluster()
    base = prepcache.CacheEntry("t|base", prepare(base_cluster, []))

    added = [Pod.from_dict(_pod_dict("new-a", cpu="450m")), Pod.from_dict(_pod_dict("new-b", cpu="150m"))]
    with base.lock:
        base.restore()
        entry = prepcache.twin_pod_delta(base, "t2|base", added, {("default", "dead")})
    assert entry is not None and entry.base_drop is not None

    live = cluster(with_new=True, without_dead=True)
    res_delta = simulate(live, [], prep=entry.prep, drop_pods=entry.base_drop)
    res_fresh = simulate(cluster(with_new=True, without_dead=True), [])

    def placed(res):
        return {
            p.metadata.name: ns.node.metadata.name
            for ns in res.node_status
            for p in ns.pods
        }

    assert placed(res_delta) == placed(res_fresh)
    assert "dead" not in placed(res_delta)
    # the delta path never re-prepared the cluster: stream length is the
    # base's plus exactly the added pods
    assert len(entry.prep.ordered) == len(base.prep.ordered) + 2


def test_mixed_node_and_pod_waves_bit_equal_to_fresh_prepare():
    """ISSUE 11 satellite (NOTES round-14): a mixed node+pod batch applied
    as node-wave-then-pod-wave — ``extend_with_nodes`` then
    ``twin_pod_delta`` on the extended entry, exactly ``flush_pending``'s
    decomposition — schedules byte-identically to a fresh full prepare of
    the post-batch cluster."""
    from opensim_tpu.engine import prepcache
    from opensim_tpu.engine.simulator import prepare, simulate

    def cluster(post=False):
        rt = ResourceTypes()
        for i in range(4):
            rt.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
        if post:
            rt.nodes.append(fx.make_fake_node("n9", "8", "16Gi"))
        if not post:
            rt.pods.append(Pod.from_dict(_pod_dict("dead", phase="Running", node="n0", cpu="300m")))
        rt.pods.append(Pod.from_dict(_pod_dict("keep", phase="Pending", cpu="200m")))
        if post:
            rt.pods.append(Pod.from_dict(_pod_dict("new-a", cpu="450m")))
        return rt

    base_cluster = cluster()
    base = prepcache.CacheEntry("m|base", prepare(base_cluster, []))
    post = cluster(post=True)
    new_nodes = [n for n in post.nodes if n.metadata.name == "n9"]
    added = [Pod.from_dict(_pod_dict("new-a", cpu="450m"))]
    with base.lock:
        base.restore()
        # node wave: arena extend keeps the lineage
        new_prep = prepcache.extend_with_nodes(base.prep, new_nodes, post, [], base_entry=base)
        assert new_prep is not None, "node wave must extend, not rebuild"
        mid = prepcache.CacheEntry("m2|base", new_prep, base=base)
        mid.base_drop = prepcache.pad_drop_mask(base.base_drop, len(new_prep.ordered))
    with mid.lock:
        # pod wave on top: bare-region insert + tombstone mask flip
        entry = prepcache.twin_pod_delta(mid, "m3|base", added, {("default", "dead")})
    assert entry is not None and entry.base_drop is not None

    res_delta = simulate(post, [], prep=entry.prep, drop_pods=entry.base_drop)
    res_fresh = simulate(cluster(post=True), [])

    def placed(res):
        return {
            p.metadata.name: ns.node.metadata.name
            for ns in res.node_status
            for p in ns.pods
        }

    assert placed(res_delta) == placed(res_fresh)
    assert "dead" not in placed(res_delta)


def test_mixed_flush_keeps_lineage_warm_end_to_end(tmp_path):
    """A node ADDED arriving in the same pending batch as pod churn used to
    drop the warm prep lineage wholesale; the wave split keeps it: no second
    full prepare, one delta_nodes + one twin_delta, and placements
    shape-equal to a polling server's full relist."""
    from opensim_tpu.utils.trace import PREP_STATS

    with _twin_server(tmp_path, pods=[_pod_dict("p1", phase="Running", node="n0")]) as (
        stub, sup, server, kc,
    ):
        code, _ = server.deploy_apps(_payload())
        assert code == 200
        full0 = PREP_STATS.counts.get("full", 0)
        dn0 = PREP_STATS.counts.get("delta_nodes", 0)
        td0 = PREP_STATS.counts.get("twin_delta", 0)

        # one mixed batch: a node joins while pods churn
        stub.upsert("/api/v1/nodes", fx.make_fake_node("n9", "8", "16Gi").raw)
        stub.upsert("/api/v1/pods", _pod_dict("p2", cpu="250m"))
        stub.delete("/api/v1/pods", "p1")
        _wait(
            lambda: len(sup.twin.materialize().nodes) == 5
            and sorted(p.metadata.name for p in sup.twin.materialize().pods) == ["p2"],
            msg="mixed batch applied to the twin",
        )
        sup.flush_pending()
        assert PREP_STATS.counts.get("full", 0) == full0, "mixed flush dropped the lineage"
        assert PREP_STATS.counts.get("delta_nodes", 0) == dn0 + 1  # node wave
        assert PREP_STATS.counts.get("twin_delta", 0) == td0 + 1  # pod wave

        code, body = server.deploy_apps(_payload())
        assert code == 200
        assert PREP_STATS.counts.get("full", 0) == full0

        polling = rest.SimonServer(kubeconfig=kc)
        code, ref = polling.deploy_apps(_payload())
        assert code == 200
        assert _shape(body) == _shape(ref)


def test_twin_pod_delta_refuses_past_compaction_threshold():
    """Pure add/delete churn must not grow the masked-row count without
    bound: past the density threshold the delta is refused (None) so the
    caller's full rebuild compacts the stream."""
    from opensim_tpu.engine import prepcache
    from opensim_tpu.engine.simulator import prepare

    rt = ResourceTypes()
    rt.nodes.append(fx.make_fake_node("n0", "64", "256Gi"))
    for i in range(100):
        rt.pods.append(Pod.from_dict(_pod_dict(f"churn-{i}", phase="Running", node="n0")))
    base = prepcache.CacheEntry("c|base", prepare(rt, []))
    with base.lock:
        base.restore()
        # 65 deletions of 100 bare pods: > max(64, len//4) masked rows
        doomed = {("default", f"churn-{i}") for i in range(65)}
        assert prepcache.twin_pod_delta(base, "c2|base", [], doomed) is None
        # under the threshold the delta still engages
        few = {("default", f"churn-{i}") for i in range(10)}
        entry = prepcache.twin_pod_delta(base, "c3|base", [], few)
        assert entry is not None and int(entry.base_drop.sum()) == 10


# ---------------------------------------------------------------------------
# end-to-end against the stub apiserver
# ---------------------------------------------------------------------------


def test_event_convergence_fingerprint_matches_full_relist(tmp_path):
    """ADDED/DELETED watch events leave the twin bit-equal (content
    fingerprint) to a fresh full relist — the bootstrap and the relist share
    one list code path, so the comparison is exact."""
    with _twin_server(tmp_path, pods=[_pod_dict("p1", phase="Running", node="n0")]) as (
        stub, sup, server, kc,
    ):
        stub.upsert("/api/v1/pods", _pod_dict("p2"))
        stub.upsert("/api/v1/pods", _pod_dict("p3", cpu="200m"))
        stub.delete("/api/v1/pods", "p1")
        _wait(
            lambda: sorted(p.metadata.name for p in sup.twin.materialize().pods) == ["p2", "p3"],
            msg="twin to apply ADDED+DELETED",
        )
        fresh, rvs = _cluster_via_rest(kc, None)
        assert sup.twin.fingerprint() == fingerprint_cluster(fresh)
        # every list captured its resourceVersion (satellite: shared list path)
        assert rvs and all(v for v in rvs.values())


def test_warm_path_single_event_is_delta_not_full_prepare(tmp_path):
    """Warm-path proof: after the first request builds the base, a pod
    ADDED/DELETED event costs one twin_delta re-encode (O(changes)) and the
    next request pays only its own app delta — PREP_STATS shows no second
    'full' prepare, and placements stay shape-equal to a polling server
    that full-relists."""
    from opensim_tpu.utils.trace import PREP_STATS

    with _twin_server(tmp_path, pods=[_pod_dict("p1", phase="Running", node="n0")]) as (
        stub, sup, server, kc,
    ):
        code, body1 = server.deploy_apps(_payload())
        assert code == 200
        full0 = PREP_STATS.counts.get("full", 0)
        delta0 = PREP_STATS.counts.get("twin_delta", 0)

        stub.upsert("/api/v1/pods", _pod_dict("p2"))
        _wait(lambda: len(sup.twin.materialize().pods) == 2, msg="ADDED applied")
        sup.flush_pending()  # deterministic maintenance (normally the tick)
        assert PREP_STATS.counts.get("twin_delta", 0) == delta0 + 1

        code, body2 = server.deploy_apps(_payload())
        assert code == 200
        assert PREP_STATS.counts.get("full", 0) == full0  # no full re-prepare

        stub.delete("/api/v1/pods", "p2")
        _wait(lambda: len(sup.twin.materialize().pods) == 1, msg="DELETED applied")
        sup.flush_pending()
        assert PREP_STATS.counts.get("twin_delta", 0) == delta0 + 2
        code, body3 = server.deploy_apps(_payload())
        assert code == 200
        assert PREP_STATS.counts.get("full", 0) == full0

        # a polling-mode server full-relisting the same cluster agrees
        polling = rest.SimonServer(kubeconfig=kc)
        code, ref = polling.deploy_apps(_payload())
        assert code == 200
        assert _shape(body3) == _shape(ref)


def test_bookmark_keepalive_resets_staleness_deadline(tmp_path):
    """BOOKMARK-only traffic keeps the twin live; silence past
    OPENSIM_WATCH_STALE_S degrades it; the next event revives it."""
    pol = {"stale_s": 0.4}
    with _twin_server(tmp_path, policy=pol, bookmark_s=0.05) as (stub, sup, server, kc):
        time.sleep(1.0)  # multiple staleness windows, bookmark traffic only
        assert sup.state() == "live"
        assert sum(n for (k, _res), n in sup.events_total.items() if k == "BOOKMARK") > 0

        stub.bookmark_interval_s = 30.0  # silence the streams
        _wait(lambda: sup.state() == "degraded", msg="staleness degradation")
        assert sup.is_stale()

        stub.upsert("/api/v1/pods", _pod_dict("wake"))
        _wait(lambda: sup.state() == "live", msg="event-driven revival")


def test_degraded_twin_tags_responses_stale(tmp_path):
    """Requests served from a degraded twin carry the existing
    X-Simon-Snapshot: stale header (same contract as the polling path's
    stale-serve)."""
    from http.server import ThreadingHTTPServer

    pol = {"stale_s": 0.4}
    with _twin_server(tmp_path, policy=pol, bookmark_s=0.05) as (stub, sup, server, kc):
        httpd = ThreadingHTTPServer(("127.0.0.1", 0), rest.make_handler(server))
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            port = httpd.server_address[1]
            body = json.dumps(_payload()).encode()

            def post():
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/api/deploy-apps", data=body, method="POST"
                )
                return urllib.request.urlopen(req)

            with post() as r:
                assert r.headers.get("X-Simon-Snapshot") is None

            stub.bookmark_interval_s = 30.0
            _wait(lambda: sup.state() == "degraded", msg="staleness degradation")
            with post() as r:
                assert r.headers.get("X-Simon-Snapshot") == "stale"

            # /metrics renders the state machine + stale-serve counters
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics") as r:
                text = r.read().decode()
            assert 'simon_watch_state{state="degraded"} 1' in text
            assert "simon_watch_events_total" in text
        finally:
            httpd.shutdown()


def test_disconnect_fault_reconnects_and_converges(tmp_path):
    with _twin_server(tmp_path) as (stub, sup, server, kc):
        faults.inject("watch.disconnect", count=1, exc="fault")
        stub.upsert("/api/v1/pods", _pod_dict("after-drop"))
        _wait(
            lambda: any(p.metadata.name == "after-drop" for p in sup.twin.materialize().pods),
            msg="convergence after injected disconnect",
        )
        _wait(lambda: sup.reconnects_total >= 1, msg="reconnect counted")
        assert faults.fault_stats().get("watch.disconnect") == 1
        fresh, _ = _cluster_via_rest(kc, None)
        _wait(lambda: sup.state() == "live", msg="live after reconnect")
        assert sup.twin.fingerprint() == fingerprint_cluster(fresh)


def test_gone_fault_relists_and_rebases(tmp_path):
    with _twin_server(tmp_path) as (stub, sup, server, kc):
        faults.inject("watch.gone", count=1, exc="fault")
        stub.upsert("/api/v1/pods", _pod_dict("post-gone"))
        _wait(lambda: sup.gone_total >= 1, msg="410 noted")
        _wait(lambda: sup.relists_total >= 1, msg="relist-and-rebase")
        _wait(
            lambda: any(p.metadata.name == "post-gone" for p in sup.twin.materialize().pods),
            msg="convergence after rebase",
        )
        fresh, _ = _cluster_via_rest(kc, None)
        assert sup.twin.fingerprint() == fingerprint_cluster(fresh)


def test_watch_410_at_the_source_raises_gone(tmp_path):
    """Protocol-level: a watch resuming from a compacted resourceVersion
    gets the ERROR event with code 410, surfaced as GoneError."""
    stub = StubApiServer().start()
    _seed(stub)
    try:
        old_rv = stub.rv()
        for i in range(3):
            stub.upsert("/api/v1/pods", _pod_dict(f"fill-{i}"))
        stub.compact()
        stub.upsert("/api/v1/pods", _pod_dict("past-compaction"))
        src = RestWatchSource(stub.kubeconfig(tmp_path), read_timeout_s=2.0)
        with pytest.raises(GoneError):
            for _ev in src.watch("pods", str(old_rv)):
                pytest.fail("events must not be delivered across a compaction gap")
    finally:
        stub.stop()


def test_dropped_event_drift_detected_and_rebased(tmp_path):
    """A lost event (watch.drop_event) silently desyncs the twin — only the
    anti-entropy pass can see it: drift is counted in simon_twin_drift_total
    and the rebase reconverges the fingerprint."""
    from opensim_tpu.obs.recorder import FLIGHT_RECORDER

    with _twin_server(tmp_path) as (stub, sup, server, kc):
        faults.inject("watch.drop_event", count=1, exc="fault")
        stub.upsert("/api/v1/pods", _pod_dict("lost"))
        _wait(lambda: faults.fault_stats().get("watch.drop_event") == 1, msg="event dropped")
        time.sleep(0.2)
        assert all(p.metadata.name != "lost" for p in sup.twin.materialize().pods)

        drift = sup.anti_entropy()
        assert drift >= 1
        assert sup.drift_total >= 1
        assert any(p.metadata.name == "lost" for p in sup.twin.materialize().pods)
        fresh, _ = _cluster_via_rest(kc, None)
        assert sup.twin.fingerprint() == fingerprint_cluster(fresh)
        lines = "\n".join(sup.metrics_lines())
        # drift is attributed by resource (ISSUE 7 satellite): the lost
        # object was a pod, so the pods series carries the repairs
        assert (
            f'simon_twin_drift_total{{resource="pods"}} '
            f"{sup.drift_by_resource.get('pods', 0)}" in lines
        )
        assert sup.drift_by_resource.get("pods", 0) >= 1
        # the anti-entropy cycle is visible in the flight recorder
        assert any(
            s["request_id"].startswith("watch-anti-entropy-")
            for s in FLIGHT_RECORDER.summaries()
        )


def test_reorder_fault_converges_by_rv(tmp_path):
    """An out-of-order delivery (watch.reorder holds an event back past its
    successor) must not desync the twin: rv-monotonic application converges
    the object set, and anti-entropy confirms zero drift."""
    with _twin_server(tmp_path) as (stub, sup, server, kc):
        faults.inject("watch.reorder", count=1, exc="fault")
        stub.upsert("/api/v1/pods", _pod_dict("first"))
        stub.upsert("/api/v1/pods", _pod_dict("second"))
        _wait(
            lambda: {p.metadata.name for p in sup.twin.materialize().pods} == {"first", "second"},
            msg="both events applied despite reordering",
        )
        assert faults.fault_stats().get("watch.reorder") == 1
        assert sup.anti_entropy() == 0


def test_bootstrap_failure_falls_back_to_polling(tmp_path, monkeypatch):
    """Watch bootstrap that cannot list keeps the server fully functional on
    the polling snapshot path (graceful --watch default-on)."""
    stub = StubApiServer().start()
    _seed(stub)
    kc = stub.kubeconfig(tmp_path)
    stub.stop()  # apiserver gone before the twin ever syncs

    pol = dict(FAST, stale_s=1.0)
    sup = WatchSupervisor(RestWatchSource(kc, read_timeout_s=1.0), policy=pol)
    try:
        assert sup.start(wait_s=0.5) is False
        assert not sup.has_synced()

        fetches = []

        def fake_fetch(kubeconfig, master=None):
            fetches.append(kubeconfig)
            rt = ResourceTypes()
            for i in range(3):
                rt.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
            return rt

        monkeypatch.setattr(rest, "cluster_from_kubeconfig", fake_fetch)
        server = rest.SimonServer(kubeconfig="/tmp/kc", watch=sup)
        sup.prep_cache = server.prep_cache
        code, body = server.deploy_apps(_payload())
        assert code == 200 and body["nodeStatus"]
        assert fetches  # served by the polling path
    finally:
        sup.stop()


def test_watch_on_requires_kubeconfig():
    """--watch on with no kubeconfig must fail loudly (exit 1), not start a
    polling/empty-cluster server the operator believes is a synced twin."""
    assert rest.serve(kubeconfig="", watch="on") == 1


def test_watch_policy_validation(monkeypatch):
    assert watch_policy()["stale_s"] == 30.0
    monkeypatch.setenv("OPENSIM_WATCH_STALE_S", "soon")
    with pytest.raises(ValueError, match="OPENSIM_WATCH_STALE_S"):
        watch_policy()
    monkeypatch.setenv("OPENSIM_WATCH_STALE_S", "0")
    with pytest.raises(ValueError, match="positive"):
        watch_policy()
    monkeypatch.setenv("OPENSIM_WATCH_STALE_S", "5")
    monkeypatch.setenv("OPENSIM_WATCH_RECONNECTS", "0")
    with pytest.raises(ValueError, match="OPENSIM_WATCH_RECONNECTS"):
        watch_policy()


# ---------------------------------------------------------------------------
# chaos gate (make chaos): mid-stream fault storm, then convergence
# ---------------------------------------------------------------------------


def test_chaos_watch_server_reconverges_shape_equal_to_full_relist(tmp_path):
    """The ISSUE 6 chaos bar: with watch.disconnect, watch.gone AND a
    dropped event injected mid-stream while the cluster mutates, the
    watch-mode server's next simulate response is shape-equal to a
    polling-mode server's answer after a fresh full relist, with the drift
    counter showing detection."""
    with _twin_server(tmp_path, pods=[_pod_dict("seed", phase="Running", node="n0")]) as (
        stub, sup, server, kc,
    ):
        code, _ = server.deploy_apps(_payload())
        assert code == 200

        faults.inject("watch.disconnect", count=1, exc="fault")
        stub.upsert("/api/v1/pods", _pod_dict("storm-a"))
        _wait(lambda: faults.fault_stats().get("watch.disconnect") == 1, msg="disconnect fired")

        faults.inject("watch.gone", count=1, exc="fault")
        stub.upsert("/api/v1/pods", _pod_dict("storm-b", cpu="250m"))
        _wait(lambda: faults.fault_stats().get("watch.gone") == 1, msg="gone fired")

        faults.inject("watch.drop_event", count=1, exc="fault")
        stub.upsert("/api/v1/pods", _pod_dict("storm-c", cpu="150m"))
        _wait(lambda: faults.fault_stats().get("watch.drop_event") == 1, msg="event dropped")

        drift = sup.anti_entropy()  # repairs whatever the drop lost
        assert drift >= 0
        _wait(
            lambda: {"storm-a", "storm-b", "storm-c"}
            <= {p.metadata.name for p in sup.twin.materialize().pods},
            msg="twin reconverged on the full mutation set",
        )
        fresh, _ = _cluster_via_rest(kc, None)
        assert sup.twin.fingerprint() == fingerprint_cluster(fresh)

        code, twin_body = server.deploy_apps(_payload())
        assert code == 200
        polling = rest.SimonServer(kubeconfig=kc)
        code, relist_body = polling.deploy_apps(_payload())
        assert code == 200
        assert _shape(twin_body) == _shape(relist_body)
        # the storm left its fingerprints in the metrics surface
        text = rest.METRICS.render(prep_cache=server.prep_cache, watch=sup)
        assert "simon_watch_reconnects_total" in text
        assert "simon_twin_drift_total" in text
        assert 'simon_faults_injected_total{point="watch.disconnect"}' in text
