"""Planner, report, chart, and server tests."""

import json
import threading
import urllib.request

from opensim_tpu.chart.render import process_chart, render_template
from opensim_tpu.engine.simulator import AppResource, simulate
from opensim_tpu.models import ResourceTypes
from opensim_tpu.models import fixtures as fx
from opensim_tpu.planner import report as report_mod
from opensim_tpu.planner.apply import Applier, Options, satisfy_resource_setting


def _write_config(tmp_path, cluster_dir, app_dir, newnode_dir):
    cfg = tmp_path / "config.yaml"
    cfg.write_text(
        f"""apiVersion: simon/v1alpha1
kind: Config
metadata: {{name: test}}
spec:
  cluster:
    customConfig: {cluster_dir}
  appList:
    - name: app
      path: {app_dir}
  newNode: {newnode_dir}
"""
    )
    return str(cfg)


def test_applier_adds_nodes_until_schedulable(tmp_path):
    cluster_dir = tmp_path / "cluster"
    app_dir = tmp_path / "app"
    newnode_dir = tmp_path / "newnode"
    for d in (cluster_dir, app_dir, newnode_dir):
        d.mkdir()
    import yaml

    (cluster_dir / "node.yaml").write_text(yaml.safe_dump(fx.make_fake_node("n1", "4", "8Gi").raw))
    (app_dir / "deploy.yaml").write_text(
        yaml.safe_dump(fx.make_fake_deployment("big", 6, "2", "2Gi").raw)
    )
    (newnode_dir / "node.yaml").write_text(yaml.safe_dump(fx.make_fake_node("tmpl", "8", "16Gi").raw))

    out_file = tmp_path / "report.txt"
    opts = Options(
        simon_config=_write_config(tmp_path, cluster_dir, app_dir, newnode_dir),
        output_file=str(out_file),
        max_new_nodes=8,
    )
    rc = Applier(opts).run()
    assert rc == 0
    text = out_file.read_text()
    assert "Simulation success!" in text
    # 6 pods × 2 CPU: n1 (4 CPU) holds 2, one new 8-CPU node holds the other 4
    assert "added 1 new node(s)" in text
    assert "√" in text  # new-node marker in the table


def test_applier_fails_without_new_node(tmp_path):
    cluster_dir = tmp_path / "cluster"
    app_dir = tmp_path / "app"
    cluster_dir.mkdir()
    app_dir.mkdir()
    import yaml

    (cluster_dir / "node.yaml").write_text(yaml.safe_dump(fx.make_fake_node("n1", "1", "1Gi").raw))
    (app_dir / "deploy.yaml").write_text(yaml.safe_dump(fx.make_fake_deployment("big", 2, "4", "8Gi").raw))
    cfg = tmp_path / "config.yaml"
    cfg.write_text(
        f"""apiVersion: simon/v1alpha1
kind: Config
metadata: {{name: test}}
spec:
  cluster: {{customConfig: {cluster_dir}}}
  appList:
    - name: app
      path: {app_dir}
"""
    )
    out_file = tmp_path / "report.txt"
    rc = Applier(Options(simon_config=str(cfg), output_file=str(out_file))).run()
    assert rc == 1
    assert "Insufficient" in out_file.read_text()


def test_satisfy_resource_setting_caps(monkeypatch):
    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n1", "4", "8Gi"))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("p", "3", "1Gi"))
    res = simulate(cluster, [AppResource("a", app)])
    ok, _ = satisfy_resource_setting(res)
    assert ok
    monkeypatch.setenv("MaxCPU", "50")
    ok, reason = satisfy_resource_setting(res)
    assert not ok and "cpu" in reason
    monkeypatch.delenv("MaxCPU")


def test_report_renders_gpu_and_storage(tmp_path):
    from opensim_tpu.models import expand

    cluster = expand.load_cluster_from_dir("example/cluster/gpushare")
    app, _ = expand.resources_from_dicts(
        expand.load_yaml_objects("example/application/gpushare")
    )
    res = simulate(cluster, [AppResource("pai_gpu", app)])
    import io

    buf = io.StringIO()
    report_mod.report(res, ["gpu"], ["pai_gpu"], out=buf)
    text = buf.getvalue()
    assert "GPU Node Resource" in text
    assert "Pod -> Node Map" in text
    assert "gpu-a-1" in text


def test_chart_render_obs_stack():
    docs = process_chart("obs", "example/application/charts/obs-stack")
    import yaml

    objs = [yaml.safe_load(d) for d in docs]
    kinds = [o.get("kind") for o in objs]
    assert "DaemonSet" in kinds and "CronJob" in kinds and "StorageClass" in kinds
    # install order: StorageClass before Deployment before CronJob
    assert kinds.index("StorageClass") < kinds.index("DaemonSet") < kinds.index("CronJob")
    # values substituted, no template syntax left
    joined = "\n".join(docs)
    assert "{{" not in joined
    assert "open-local" in joined


def test_template_subset():
    ctx = {"Values": {"a": {"b": "x"}, "flag": True, "n": 3}, "Release": {"Name": "r1"}}
    assert render_template("v: {{ .Values.a.b }}", ctx) == "v: x"
    assert render_template("{{ .Release.Name }}", ctx) == "r1"
    assert render_template("{{- if .Values.flag }}yes{{- else }}no{{- end }}", ctx) == "yes"
    assert render_template("{{- if .Values.missing }}yes{{- else }}no{{- end }}", ctx) == "no"
    assert render_template("{{ int .Values.n }}", ctx) == "3"
    assert render_template("{{ .Values.a.b | quote }}", ctx) == '"x"'


def test_rest_server_deploy_and_healthz():
    from opensim_tpu.server.rest import SimonServer, make_handler
    from http.server import ThreadingHTTPServer

    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n1", "8", "16Gi"))
    server = SimonServer(base_cluster=cluster)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(server))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz") as r:
            assert json.load(r)["status"] == "ok"
        body = json.dumps(
            {"deployments": [fx.make_fake_deployment("web", 3, "500m", "512Mi").raw]}
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/deploy-apps", data=body, method="POST"
        )
        with urllib.request.urlopen(req) as r:
            resp = json.load(r)
        assert resp["unscheduledPods"] == []
        assert resp["nodeStatus"][0]["node"] == "n1"
        assert len(resp["nodeStatus"][0]["pods"]) == 3
        # malformed body → 400
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/deploy-apps", data=b"{not json", method="POST"
        )
        try:
            urllib.request.urlopen(req)
            assert False, "expected 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        httpd.shutdown()


def test_masked_prep_reuse_matches_fresh_simulate():
    """Planner prep reuse (VERDICT r4 #5): a masked re-simulation over the
    full-candidate Prepared must equal a fresh simulate of the sub-cluster
    — placements (by workload counts per node), unschedulable reasons, and
    the report-visible node set."""
    import collections
    import copy

    import numpy as np

    from opensim_tpu.engine.simulator import prepare
    from opensim_tpu.models import expand

    cluster = ResourceTypes()
    for i in range(4):
        cluster.nodes.append(
            fx.make_fake_node(
                f"n{i}", "8", "16Gi", "110",
                fx.with_labels({"topology.kubernetes.io/zone": f"z{i % 2}"}),
            )
        )
    cluster.daemon_sets.append(fx.make_fake_daemon_set("logger", "100m", "64Mi"))
    rt = ResourceTypes()
    rt.deployments.append(fx.make_fake_deployment("web", 120, "1", "2Gi"))
    apps = [AppResource("web", rt)]

    template = fx.make_fake_node("tmpl", "16", "32Gi")
    candidates = expand.new_fake_nodes(template, 8)
    full = copy.copy(cluster)
    full.nodes = list(cluster.nodes) + candidates

    def agg(res):
        out = {}
        for ns in res.node_status:
            c = collections.Counter()
            for p in ns.pods:
                kind = p.metadata.annotations.get("simon/workload-kind")
                wl = p.metadata.annotations.get("simon/workload-name") or p.metadata.name
                c["web" if kind == "ReplicaSet" else wl] += 1
            out[ns.node.metadata.name] = dict(c)
        return out

    for k in (0, 3, 8):
        sub = copy.copy(cluster)
        sub.nodes = list(cluster.nodes) + candidates[:k]
        prep_full = prepare(full, apps)  # fresh each k: decode mutates pods
        mask = np.zeros(np.asarray(prep_full.ec_np.node_valid).shape[0], bool)
        mask[: len(sub.nodes)] = True
        masked = simulate(sub, apps, prep=prep_full, node_valid=mask)
        fresh = simulate(sub, apps)
        assert agg(masked) == agg(fresh), f"k={k}"
        assert sorted(u.reason for u in masked.unscheduled_pods) == sorted(
            u.reason for u in fresh.unscheduled_pods
        ), f"k={k}"
        # the masked run reports exactly the sub-cluster's nodes
        assert [ns.node.metadata.name for ns in masked.node_status] == [
            n.metadata.name for n in sub.nodes
        ]


def test_interactive_scripted_session_routes_through_out(tmp_path):
    """ISSUE 3 satellite (VERDICT r4 weak #6): interactive-mode prompts no
    longer bypass ``self.out`` with ad-hoc ``input()`` calls — the prompt
    text renders through ``self.out`` and the replies come from the
    injectable ``input_fn``, so a whole survey session runs scripted."""
    import io

    import yaml

    cluster_dir = tmp_path / "cluster"
    app_dir = tmp_path / "app"
    newnode_dir = tmp_path / "newnode"
    for d in (cluster_dir, app_dir, newnode_dir):
        d.mkdir()
    (cluster_dir / "node.yaml").write_text(yaml.safe_dump(fx.make_fake_node("n1", "4", "8Gi").raw))
    (app_dir / "deploy.yaml").write_text(
        yaml.safe_dump(fx.make_fake_deployment("big", 6, "2", "2Gi").raw)
    )
    (newnode_dir / "node.yaml").write_text(
        yaml.safe_dump(fx.make_fake_node("tmpl", "8", "16Gi").raw)
    )
    applier = Applier(
        Options(
            simon_config=_write_config(tmp_path, cluster_dir, app_dir, newnode_dir),
            interactive=True,
        )
    )
    out = io.StringIO()
    applier.out = out
    # scripted session: show the unschedulable pods, add 1 node (8 CPU —
    # enough for the 4 remaining 2-CPU pods), then report all nodes
    script = iter(["show", "add", "1", ""])
    applier.input_fn = lambda: next(script)
    rc = applier.run()
    text = out.getvalue()
    assert rc == 0, text
    # prompt output went through self.out, not stdout
    assert "you can:" in text
    assert "1) Show unschedulable pods" in text
    assert "input node number > " in text
    assert "nodes to report pods for" in text
    # the Show branch listed reasons through self.out too
    assert "Insufficient" in text
    assert "Simulation success!" in text


def test_interactive_eof_exits_cleanly(tmp_path):
    import io

    import yaml

    cluster_dir = tmp_path / "cluster"
    app_dir = tmp_path / "app"
    cluster_dir.mkdir()
    app_dir.mkdir()
    (cluster_dir / "node.yaml").write_text(yaml.safe_dump(fx.make_fake_node("n1", "1", "1Gi").raw))
    (app_dir / "deploy.yaml").write_text(
        yaml.safe_dump(fx.make_fake_deployment("big", 2, "4", "8Gi").raw)
    )
    cfg = tmp_path / "config.yaml"
    cfg.write_text(
        f"""apiVersion: simon/v1alpha1
kind: Config
metadata: {{name: test}}
spec:
  cluster: {{customConfig: {cluster_dir}}}
  appList:
    - name: app
      path: {app_dir}
"""
    )
    applier = Applier(Options(simon_config=str(cfg), interactive=True))
    out = io.StringIO()
    applier.out = out

    def eof():
        raise EOFError

    applier.input_fn = eof
    rc = applier.run()
    assert rc == 1  # EOF selects Exit
    assert "can not be scheduled" in out.getvalue()


def test_capacity_sweep_with_differing_profiles_matches_segmented_simulate(tmp_path):
    """NOTES.md round-5 rough edge, closed (ISSUE 12 satellite): a capacity
    sweep whose pod stream references DIFFERING scheduler profiles used to
    raise out of the batched pipeline (the planner kept a sequential
    per-count fallback). ``sweep_auto`` now routes mixed-profile streams
    through ``sweep_segmented`` — this gates the planner path against the
    segmented masked simulate, count for count, placement for placement."""
    import numpy as np
    import yaml

    from opensim_tpu.engine.simulator import (
        prepare,
        restore_bind_state,
        snapshot_bind_state,
    )
    from opensim_tpu.models import expand
    from opensim_tpu.parallel import scenarios

    cluster_dir = tmp_path / "cluster"
    app_dir = tmp_path / "app"
    newnode_dir = tmp_path / "newnode"
    for d in (cluster_dir, app_dir, newnode_dir):
        d.mkdir()
    (cluster_dir / "node.yaml").write_text(
        yaml.safe_dump(fx.make_fake_node("n0", "4", "16Gi").raw)
    )
    # two deployments on DIFFERING profiles: default-scheduler plus a
    # score-disabled "lean" profile (contiguous segments in stream order)
    default_dep = fx.make_fake_deployment("plain", 4, "1", "256Mi")
    lean_dep = fx.make_fake_deployment("lean", 4, "1", "256Mi")
    lean_dep.raw["spec"]["template"]["spec"]["schedulerName"] = "lean"
    (app_dir / "a-plain.yaml").write_text(yaml.safe_dump(default_dep.raw))
    (app_dir / "b-lean.yaml").write_text(yaml.safe_dump(lean_dep.raw))
    (newnode_dir / "node.yaml").write_text(
        yaml.safe_dump(fx.make_fake_node("tmpl", "4", "16Gi").raw)
    )
    sched = tmp_path / "sched.yaml"
    sched.write_text(
        """kind: KubeSchedulerConfiguration
profiles:
  - schedulerName: default-scheduler
  - schedulerName: lean
    plugins:
      score:
        disabled:
          - name: "*"
"""
    )
    opts = Options(
        simon_config=_write_config(tmp_path, cluster_dir, app_dir, newnode_dir),
        default_scheduler_config=str(sched),
        max_new_nodes=4,
    )
    applier = Applier(opts)
    cluster = applier.load_cluster()
    apps = applier.load_apps()
    template = applier.load_new_node()
    candidates = expand.new_fake_nodes(template, 4)
    full = ResourceTypes()
    full.nodes = list(cluster.nodes) + candidates
    full.pods = list(cluster.pods)
    prep = prepare(full, apps)
    assert prep is not None
    n_real = len(cluster.nodes)
    ks = [0, 1, 2, 3, 4]

    # the planner's batched verdicts (would have raised before the fix)
    ok = applier._feasible_counts(prep, n_real, ks)
    # 8 one-cpu pods vs one 4-cpu node: infeasible at k=0, feasible with
    # one 4-cpu candidate enabled
    assert ok[0] is False or ok[0] == np.False_
    assert bool(ok[1]) and bool(ok[4])

    # count-for-count oracle: the segmented masked simulate of the SAME
    # prep (the old sequential fallback, now the gating reference)
    res, node_valid = scenarios.sweep_counts(
        prep, n_real, ks, config=applier.sched_config
    )
    chosen = np.asarray(res.chosen)
    N = np.asarray(prep.ec_np.node_valid).shape[0]
    name_to_idx = {name: i for i, name in enumerate(prep.meta.node_names)}
    snap = snapshot_bind_state(prep)
    for s, k in enumerate(ks):
        sub = ResourceTypes()
        sub.nodes = full.nodes[: n_real + k]
        sub.pods = list(full.pods)
        mask = np.zeros(N, dtype=bool)
        mask[: n_real + k] = True
        solo = simulate(
            sub, apps, sched_config=applier.sched_config, prep=prep, node_valid=mask
        )
        solo_chosen = {}
        for ns in solo.node_status:
            for p in ns.pods:
                solo_chosen[(p.metadata.namespace, p.metadata.name)] = name_to_idx[
                    ns.node.metadata.name
                ]
        restore_bind_state(prep, snap)
        for i, pod in enumerate(prep.ordered):
            key = (pod.metadata.namespace, pod.metadata.name)
            assert int(chosen[s, i]) == solo_chosen.get(key, -1), (
                f"scenario k={k} pod {key}: sweep chose {int(chosen[s, i])}, "
                f"segmented simulate chose {solo_chosen.get(key, -1)}"
            )
