"""Memory observatory + compile telemetry + phase profiles (ISSUE 12)."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from opensim_tpu.engine import prepcache
from opensim_tpu.models import ResourceTypes
from opensim_tpu.models import fixtures as fx
from opensim_tpu.obs import footprint
from opensim_tpu.server import rest


def _cluster(nodes=6, bound=12):
    rt = ResourceTypes()
    for i in range(nodes):
        rt.nodes.append(fx.make_fake_node(f"n{i}", "16", "64Gi"))
    for i in range(bound):
        rt.pods.append(
            fx.make_fake_pod(f"b{i:02d}", "500m", "1Gi", fx.with_node_name(f"n{i % nodes}"))
        )
    return rt


def _payload(name="web", replicas=3):
    return {"deployments": [fx.make_fake_deployment(name, replicas, "250m", "512Mi").raw]}


# ---------------------------------------------------------------------------
# arena accounting
# ---------------------------------------------------------------------------


def test_entry_footprint_attributes_arena_fields_by_policy_dtype():
    server = rest.SimonServer(base_cluster=_cluster())
    assert server.deploy_apps(_payload())[0] == 200
    cache = footprint.prepcache_footprint(server.prep_cache, include_fields=True)
    assert cache["entries"], "deploy must populate the cache"
    entry = cache["entries"][0]
    assert entry["bytes"] > 0
    # every field carries bytes/dtype/shape, and the dtype classes are the
    # encoder policy vocabulary (a foreign dtype would land in "other")
    assert "alloc" in entry["fields"]
    assert entry["fields"]["alloc"]["dtype"] == "float32"
    assert set(entry["dtypes"]) <= {"float32", "int32", "int64", "bool", "other"}
    assert "off_policy_fields" not in entry  # the policy holds repo-wide
    assert sum(entry["dtypes"].values()) == entry["bytes"]


def test_cache_totals_reconcile_with_entry_sums_and_dedup_shared_leaves():
    """The ISSUE 12 acceptance criterion: totals == Σ per-entry unique
    bytes, with delta entries' shared base leaves counted exactly once."""
    server = rest.SimonServer(base_cluster=_cluster())
    for k in range(3):
        assert server.deploy_apps(_payload(f"app-{k}"))[0] == 200
    cache = footprint.prepcache_footprint(server.prep_cache)
    assert len(cache["entries"]) >= 2
    assert cache["total_bytes"] == sum(e["unique_bytes"] for e in cache["entries"])
    # derived entries alias the base's unchanged arenas: dedup must bite
    assert cache["shared_bytes"] > 0
    assert sum(cache["dtypes"].values()) == cache["total_bytes"]


def test_twin_delta_entry_reports_lineage_and_drop_density():
    server = rest.SimonServer(base_cluster=_cluster())
    assert server.deploy_apps(_payload())[0] == 200
    base_key = next(
        e.key for e in server.prep_cache.entries_snapshot() if e.key.endswith("|base")
    )
    base = server.prep_cache.get(base_key)
    with base.lock:
        base.restore()
        derived = prepcache.twin_pod_delta(
            base, base_key + "|churn",
            [fx.make_fake_pod("new-pod", "250m", "512Mi")],
            {("default", "b00"), ("default", "b01")},
        )
    assert derived is not None
    fp = footprint.entry_footprint(derived)
    assert fp["lineage_depth"] == 1
    assert fp["drop_density"] > 0
    assert fp["pods"] == len(derived.prep.ordered)


def test_compaction_counter_bumps_on_density_refusal():
    rt = _cluster(nodes=4, bound=80)
    server = rest.SimonServer(base_cluster=rt)
    assert server.deploy_apps(_payload())[0] == 200
    base_key = next(
        e.key for e in server.prep_cache.entries_snapshot() if e.key.endswith("|base")
    )
    base = server.prep_cache.get(base_key)
    before = prepcache.compactions_total()
    removed = {("default", f"b{i:02d}") for i in range(70)}  # > the 64-row floor
    with base.lock:
        base.restore()
        refused = prepcache.twin_pod_delta(base, base_key + "|x", [], removed)
    assert refused is None
    assert prepcache.compactions_total() == before + 1


def test_process_memory_and_observatory_watermark():
    proc = footprint.process_memory()
    assert proc["rss_bytes"] > 0
    assert proc["rss_peak_bytes"] >= proc["rss_bytes"]
    obs = footprint.MemoryObservatory()
    first = obs.sample_process()
    again = obs.sample_process()
    assert again["rss_peak_bytes"] >= first["rss_peak_bytes"]  # monotone peak


def test_memory_rows_parity_with_cluster_report(tmp_path):
    """simon top --mem parity: the report JSON's memory rows ARE the rows
    the text renderer prints (byte-equal, like every report table)."""
    from opensim_tpu.obs.capacity import format_top
    from opensim_tpu.obs.footprint import memory_rows

    server = rest.SimonServer(base_cluster=_cluster())
    assert server.deploy_apps(_payload())[0] == 200
    report = server.cluster_report(probe_headroom=False, include_memory=True)
    rows = report["memory"]["rows"]
    assert rows[0] == ["Memory", "Value"]
    assert rows == memory_rows(report["memory"]["summary"])
    rendered = format_top(report)
    for row in rows:
        for cell in row:
            assert cell in rendered
    # without ?mem=1 the block is absent and the renderer skips it
    bare = server.cluster_report(probe_headroom=False)
    assert "memory" not in bare
    assert "process RSS" not in format_top(bare)


# ---------------------------------------------------------------------------
# compile telemetry
# ---------------------------------------------------------------------------


def test_observed_jit_call_records_compiles_with_cause_attribution():
    import jax
    import jax.numpy as jnp

    from opensim_tpu.obs import profile

    watch = profile.CompileWatch()
    orig = profile.COMPILES
    profile.COMPILES = watch
    try:
        fitted = jax.jit(lambda x, k=2: x * k, static_argnames=("k",))
        profile.observed_jit_call("toy", fitted, (jnp.ones((4,)),), {"k": 2})
        profile.observed_jit_call("toy", fitted, (jnp.ones((4,)),), {"k": 2})  # warm
        profile.observed_jit_call("toy", fitted, (jnp.ones((8,)),), {"k": 2})  # shape
        profile.observed_jit_call(
            "toy", fitted, (jnp.ones((8,), jnp.int32),), {"k": 2}
        )  # dtype
        profile.observed_jit_call("toy", fitted, (jnp.ones((8,), jnp.int32),), {"k": 3})  # static
        snap = watch.snapshot()["boundaries"]["toy"]
        assert snap["compiles"] == 4  # the warm call recorded nothing
        assert snap["causes"] == {"first": 1, "shape": 1, "dtype": 1, "static": 1}
        assert snap["distinct_signatures"] == 4
        assert snap["seconds"] > 0
    finally:
        profile.COMPILES = orig


def test_schedule_pods_boundary_is_instrumented():
    """An XLA-path simulate must show up at the schedule_pods boundary
    (the C++ engine is bypassed via the env knob)."""
    import os

    from opensim_tpu.engine.simulator import AppResource, simulate
    from opensim_tpu.obs import profile

    rt = _cluster(nodes=3, bound=0)
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("solo", "100m", "128Mi"))
    os.environ["OPENSIM_DISABLE_NATIVE"] = "1"
    try:
        before = (
            profile.COMPILES.snapshot()["boundaries"]
            .get("schedule_pods", {})
            .get("compiles", 0)
        )
        res = simulate(rt, [AppResource("a", app)])
        assert not res.unscheduled_pods
        after = (
            profile.COMPILES.snapshot()["boundaries"]
            .get("schedule_pods", {})
            .get("compiles", 0)
        )
        # at least one compile OR the signature was already warm from an
        # earlier test in this process — the boundary must exist either way
        assert "schedule_pods" in profile.COMPILES.snapshot()["boundaries"] or after > before
    finally:
        del os.environ["OPENSIM_DISABLE_NATIVE"]


def test_jitcache_stats_counts_files(tmp_path, monkeypatch):
    from opensim_tpu.utils import jitcache

    cache_dir = tmp_path / "jit"
    cache_dir.mkdir()
    (cache_dir / "a.bin").write_bytes(b"x" * 100)
    (cache_dir / "b.bin").write_bytes(b"y" * 50)
    monkeypatch.setattr(jitcache, "_ACTIVE_DIR", str(cache_dir))
    stats = jitcache.cache_stats()
    assert stats == {"dir": str(cache_dir), "files": 2, "bytes": 150}


# ---------------------------------------------------------------------------
# phase profiles
# ---------------------------------------------------------------------------


def test_phase_profile_folds_exclusive_time_and_quantiles():
    from opensim_tpu.obs import trace as tracing
    from opensim_tpu.obs.profile import PhaseProfile

    prof = PhaseProfile()
    for _ in range(4):
        tr = tracing.TraceContext("deploy-apps")
        with tracing.trace_scope(tr):
            with tr.span("prepare"):
                with tr.span("encode"):
                    time.sleep(0.002)
                time.sleep(0.001)
        tr.finish()
        prof.observe_trace(tr)
    snap = prof.snapshot()
    assert snap["traces"] == 4
    prepare = snap["spans"]["prepare"]
    encode = snap["spans"]["encode"]
    assert prepare["count"] == 4 and encode["count"] == 4
    # exclusive time subtracts the encode child from prepare
    assert prepare["exclusive_seconds"] < prepare["seconds"]
    assert prepare["seconds"] >= encode["seconds"]
    assert prepare["p99_s"] >= prepare["p50_s"] >= 0
    assert "deploy-apps" in snap["endpoints"]


def test_debug_endpoints_and_cli_render(tmp_path):
    """GET /api/debug/memory + /api/debug/profile over real HTTP, and the
    simon mem / simon profile CLIs against them."""
    from http.server import ThreadingHTTPServer

    from opensim_tpu.cli.main import build_parser, run_mem, run_profile

    server = rest.SimonServer(base_cluster=_cluster())
    assert server.deploy_apps(_payload())[0] == 200
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), rest.make_handler(server))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        url = f"http://127.0.0.1:{port}"
        with urllib.request.urlopen(f"{url}/api/debug/memory") as resp:
            mem = json.load(resp)
        assert mem["prepcache"]["total_bytes"] > 0
        assert mem["process"]["rss_bytes"] > 0
        assert "fields" in mem["prepcache"]["entries"][0]
        with urllib.request.urlopen(f"{url}/api/debug/memory?fields=0") as resp:
            lean = json.load(resp)
        assert "fields" not in lean["prepcache"]["entries"][0]
        with urllib.request.urlopen(f"{url}/api/debug/profile") as resp:
            prof = json.load(resp)
        assert prof["phases"]["traces"] >= 1
        assert "backend" in prof["compiles"]

        parser = build_parser()
        import contextlib
        import io

        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = run_mem(parser.parse_args(["mem", "--url", url]))
        assert rc == 0
        text = out.getvalue()
        assert "prep cache:" in text and "process: RSS" in text
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = run_profile(parser.parse_args(["profile", "--url", url, "--json"]))
        assert rc == 0
        assert json.loads(out.getvalue())["phases"]["traces"] >= 1
    finally:
        httpd.shutdown()
        server.close()


def test_mem_ticker_env_knob(monkeypatch):
    monkeypatch.setenv("OPENSIM_MEM_TICKER_S", "0")
    obs = footprint.MemoryObservatory()
    obs.start_ticker()
    assert obs._ticker is None  # 0 disables
    monkeypatch.setenv("OPENSIM_MEM_TICKER_S", "not-a-number")
    assert footprint.mem_ticker_s() == 10.0  # degrade-with-warning contract
