"""Scenario sweep + defragmentation tests on the 8-device virtual CPU mesh."""

import numpy as np
import pytest

from opensim_tpu.engine.simulator import AppResource, prepare
from opensim_tpu.models import ResourceTypes
from opensim_tpu.models import fixtures as fx
from opensim_tpu.parallel import scenarios
from opensim_tpu.planner.defrag import plan_drains


def _setup(n_nodes=6, replicas=8):
    cluster = ResourceTypes()
    for i in range(n_nodes):
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("web", replicas, "2", "2Gi"))
    return cluster, [AppResource("a", app)]


def test_sweep_over_node_counts_sharded():
    cluster, apps = _setup(n_nodes=6, replicas=16)  # 16 pods × 2cpu = 32 cpu; 6×8=48
    prep = prepare(cluster, apps)
    N = prep.ec.node_valid.shape[0]
    P = len(prep.ordered)
    # scenario s enables s+1 nodes
    S = 6
    node_valid = np.zeros((S, N), dtype=bool)
    for s in range(S):
        node_valid[s, : s + 1] = True
    pod_valid = np.ones((S, P), dtype=bool)
    res = scenarios.sweep(
        prep.ec, prep.st0, prep.tmpl_ids, prep.forced, node_valid, pod_valid,
        mesh=scenarios.default_mesh(), features=prep.features,
    )
    unscheduled = np.asarray(res.unscheduled)
    # each 8-cpu node fits 4 pods of 2 cpu; 16 pods need >= 4 nodes
    assert unscheduled.tolist() == [12, 8, 4, 0, 0, 0]
    # monotone: more nodes never hurts
    assert all(unscheduled[i] >= unscheduled[i + 1] for i in range(S - 1))


def test_defrag_drain_plans():
    # 3 nodes, light load: any single node is drainable
    cluster, apps = _setup(n_nodes=3, replicas=3)
    result = plan_drains(cluster, apps)
    assert len(result.plans) == 3
    assert all(p.feasible for p in result.plans)

    # tight load: 12 pods × 2cpu = 24 cpu on 3×8 = 24 cpu — no drain possible
    cluster, apps = _setup(n_nodes=3, replicas=12)
    result = plan_drains(cluster, apps)
    assert all(not p.feasible for p in result.plans)
    assert all(p.unscheduled == 4 for p in result.plans)


def test_defrag_reschedules_prebound_pods():
    cluster, apps = _setup(n_nodes=3, replicas=0)
    # a pod pre-bound to n0 must be rescheduled when n0 drains
    cluster.pods.append(fx.make_fake_pod("pinned", "1", "1Gi", fx.with_node_name("n0")))
    result = plan_drains(cluster, apps)
    by_node = {p.node: p for p in result.plans}
    assert by_node["n0"].feasible  # pod fits elsewhere


@pytest.mark.slow
def test_fastpath_sweep_matches_xla_sweep(monkeypatch):
    """The megakernel-backed sweep must agree with the vmapped XLA sweep on
    unscheduled counts, placements, and final usage."""
    monkeypatch.setenv("OPENSIM_FASTPATH", "interpret")
    from opensim_tpu.engine import fastpath

    cluster, apps = _setup(n_nodes=6, replicas=16)
    prep = prepare(cluster, apps, node_pad=128)
    assert fastpath.applicable(prep)
    N = prep.ec.node_valid.shape[0]
    P = len(prep.ordered)
    S = 6
    node_valid = np.zeros((S, N), dtype=bool)
    for s in range(S):
        node_valid[s, : s + 1] = True
    pod_valid = np.ones((S, P), dtype=bool)
    forced = np.broadcast_to(prep.forced, (S, P)).copy()

    want = scenarios.sweep(
        prep.ec, prep.st0, prep.tmpl_ids, prep.forced, node_valid, pod_valid,
        features=prep.features,
    )
    got_unsched, got_used, got_chosen, got_vg = fastpath.sweep(
        prep, node_valid, pod_valid, forced, interpret=True
    )
    np.testing.assert_array_equal(got_unsched, np.asarray(want.unscheduled))
    np.testing.assert_array_equal(got_chosen, np.asarray(want.chosen)[:, :P])
    np.testing.assert_allclose(got_used, np.asarray(want.used), rtol=1e-5)
    np.testing.assert_allclose(got_vg, np.asarray(want.vg_used), rtol=1e-5)


@pytest.mark.slow
def test_fastpath_sweep_large_batch(monkeypatch):
    """A larger scenario batch (S=40) through the single-dispatch vmapped
    megakernel still matches the XLA sweep — guards the batched-grid path
    (scratch reinit per scenario, unbatched table sharing)."""
    monkeypatch.setenv("OPENSIM_FASTPATH", "interpret")
    from opensim_tpu.engine import fastpath

    cluster, apps = _setup(n_nodes=8, replicas=24)
    prep = prepare(cluster, apps, node_pad=128)
    assert fastpath.applicable(prep)
    N = prep.ec.node_valid.shape[0]
    P = len(prep.ordered)
    S = 40
    rng = np.random.RandomState(7)
    node_valid = np.zeros((S, N), dtype=bool)
    base = np.asarray(prep.ec.node_valid)
    for s in range(S):
        node_valid[s] = base
        # drain a random real node per scenario
        node_valid[s, rng.randint(0, 8)] = False
    pod_valid = np.ones((S, P), dtype=bool)
    forced = np.broadcast_to(prep.forced, (S, P)).copy()

    want = scenarios.sweep(
        prep.ec, prep.st0, prep.tmpl_ids, prep.forced, node_valid, pod_valid,
        features=prep.features,
    )
    got_unsched, got_used, got_chosen, got_vg = fastpath.sweep(
        prep, node_valid, pod_valid, forced, interpret=True
    )
    np.testing.assert_array_equal(got_unsched, np.asarray(want.unscheduled))
    np.testing.assert_array_equal(got_chosen, np.asarray(want.chosen)[:, :P])
    np.testing.assert_allclose(got_used, np.asarray(want.used), rtol=1e-5)
    np.testing.assert_allclose(got_vg, np.asarray(want.vg_used), rtol=1e-5)


@pytest.mark.parametrize("seed", [13, 47])
@pytest.mark.slow
def test_fastpath_sweep_fuzz_feature_rich(monkeypatch, seed):
    """Batched-sweep differential fuzz: random FEATURE-RICH workloads
    (gpu/local/ports/interpod/spread/avoid from the fastpath fuzz
    generators) through the single-dispatch vmapped megakernel vs the XLA
    sweep, with per-scenario drains AND per-scenario forced-mask releases
    (the defrag shape). This is the strongest interpret-mode evidence for
    the batched kernel awaiting compiled-Mosaic validation."""
    import random as _random

    monkeypatch.setenv("OPENSIM_FASTPATH", "interpret")
    from opensim_tpu.engine import fastpath
    from test_fastpath_fuzz import random_app, random_cluster

    rng = _random.Random(seed)
    cluster = random_cluster(rng, rng.randrange(8, 14))
    apps = [AppResource("fuzz", random_app(rng, rng.randrange(3, 6)))]
    prep = prepare(cluster, apps, node_pad=128)
    if prep is None or not fastpath.applicable(prep):
        pytest.skip("generated workload outside fast-path bounds")
    N = prep.ec.node_valid.shape[0]
    P = len(prep.ordered)
    S = 12
    nrng = np.random.RandomState(seed)
    base = np.asarray(prep.ec.node_valid)
    node_valid = np.zeros((S, N), bool)
    forced = np.broadcast_to(prep.forced, (S, P)).copy()
    for s in range(S):
        node_valid[s] = base
        drain = nrng.randint(0, int(base.sum()))
        node_valid[s, drain] = False
        # defrag semantics: pods pinned to the drained node become free
        for j, pod in enumerate(prep.ordered):
            if prep.forced[j] and pod.spec.node_name == prep.meta.node_names[drain]:
                forced[s, j] = False
    pod_valid = np.ones((S, P), bool)

    want = scenarios.sweep(
        prep.ec, prep.st0, prep.tmpl_ids, prep.forced, node_valid, pod_valid,
        features=prep.features, forced_masks=forced,
    )
    got_unsched, got_used, got_chosen, got_vg = fastpath.sweep(
        prep, node_valid, pod_valid, forced, interpret=True
    )
    np.testing.assert_array_equal(got_unsched, np.asarray(want.unscheduled))
    np.testing.assert_array_equal(got_chosen, np.asarray(want.chosen)[:, :P])
    np.testing.assert_allclose(got_used, np.asarray(want.used), rtol=1e-5)
    np.testing.assert_allclose(got_vg, np.asarray(want.vg_used), rtol=1e-5)


@pytest.mark.slow
def test_fastpath_sweep_big_u_mode(monkeypatch):
    """Batched sweep with the template tables in HBM (big-U per-step DMA)
    — the combination of the two round-3 envelope features, previously
    only tested separately."""
    monkeypatch.setenv("OPENSIM_FASTPATH", "interpret")
    from opensim_tpu.engine import fastpath

    cluster, apps = _setup(n_nodes=6, replicas=8)
    # inflate the template space so big_u=True is meaningful
    extra = ResourceTypes()
    for i in range(40):
        extra.pods.append(fx.make_fake_pod(f"u{i:03d}", f"{50 + i}m", "64Mi"))
    apps = apps + [AppResource("bigu", extra)]
    prep = prepare(cluster, apps, node_pad=128)
    assert fastpath.applicable(prep)
    N = prep.ec.node_valid.shape[0]
    P = len(prep.ordered)
    S = 5
    node_valid = np.zeros((S, N), bool)
    for s in range(S):
        node_valid[s, : s + 2] = True
    pod_valid = np.ones((S, P), bool)
    forced = np.broadcast_to(prep.forced, (S, P)).copy()

    want = scenarios.sweep(
        prep.ec, prep.st0, prep.tmpl_ids, prep.forced, node_valid, pod_valid,
        features=prep.features,
    )
    got_unsched, got_used, got_chosen, got_vg = fastpath.sweep(
        prep, node_valid, pod_valid, forced, interpret=True, big_u=True
    )
    np.testing.assert_array_equal(got_unsched, np.asarray(want.unscheduled))
    np.testing.assert_array_equal(got_chosen, np.asarray(want.chosen)[:, :P])
    np.testing.assert_allclose(got_used, np.asarray(want.used), rtol=1e-5)
    np.testing.assert_allclose(got_vg, np.asarray(want.vg_used), rtol=1e-5)
