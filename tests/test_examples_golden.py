"""Golden regression tests over the reference repo's example inputs: the
scheduled/unschedulable structure of each public example must stay stable
across engine changes (placements may legally differ on ties, counts not)."""

import pytest

from opensim_tpu.engine.simulator import AppResource, simulate
from opensim_tpu.models import expand

REF = "/root/reference/example"


def _app(name):
    rt, _ = expand.resources_from_dicts(expand.load_yaml_objects(f"{REF}/application/{name}"))
    return rt


def test_demo1_simple():
    cluster = expand.load_cluster_from_dir(f"{REF}/cluster/demo_1")
    res = simulate(cluster, [AppResource("simple", _app("simple"))])
    # 8-replica STS with hostname anti-affinity on a 4-node cluster: exactly
    # 4 replicas cannot schedule, everything else fits
    assert len(res.unscheduled_pods) == 4
    assert all(u.pod.metadata.name.startswith("busybox-sts-new-") for u in res.unscheduled_pods)
    assert all("inter-pod affinity" in u.reason for u in res.unscheduled_pods)
    assert sum(len(ns.pods) for ns in res.node_status) == 33


def test_demo1_open_local():
    cluster = expand.load_cluster_from_dir(f"{REF}/cluster/demo_1")
    res = simulate(cluster, [AppResource("open_local", _app("open_local"))])
    # one worker with local storage: a single LVM+device pod fits, the other
    # replicas run out of exclusive devices (masters are tainted/storage-less)
    assert len(res.unscheduled_pods) == 3
    assert all("local storage" in u.reason for u in res.unscheduled_pods)


def test_gpushare_cluster():
    cluster = expand.load_cluster_from_dir(f"{REF}/cluster/gpushare")
    res = simulate(cluster, [AppResource("pai_gpu", _app("gpushare"))])
    assert not res.unscheduled_pods
    placed = {p.metadata.name: ns.node.metadata.name for ns in res.node_status for p in ns.pods}
    assert len(placed) == 9
    # the two annotated GPU pods must carry device assignments
    by_name = {p.metadata.name: p for ns in res.node_status for p in ns.pods}
    assert by_name["gpu-pod-00"].metadata.annotations.get("alibabacloud.com/gpu-index") is not None
    assert by_name["gpu-pod-02"].metadata.annotations.get("alibabacloud.com/gpu-index") is not None


@pytest.mark.parametrize("app_name,expect_pods", [("complicate", 45), ("more_pods", 200)])
def test_app_expansion_counts(app_name, expect_pods):
    cluster = expand.load_cluster_from_dir(f"{REF}/cluster/demo_1")
    app = _app(app_name)
    pods = expand.generate_pods_from_resources(app, cluster.nodes)
    assert len(pods) == expect_pods
