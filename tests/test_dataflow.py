"""The interprocedural dataflow engine (analysis/dataflow.py): CFG shape
and reaching definitions, effect inference as a call-graph fixpoint, jit
region tracking through decorators/partials/markers, the forward taint
lattice (flow-sensitive, sanitizer-aware, interprocedural via summaries),
the tracer-leak pass, and the cross-language ABI parsers."""

import ast
import os
import textwrap

from opensim_tpu.analysis import abi
from opensim_tpu.analysis import dataflow as dfm
from opensim_tpu.analysis.core import ProjectContext, _make_context

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _project(src, path="opensim_tpu/server/fixture.py"):
    ctx, err = _make_context(textwrap.dedent(src), path)
    assert err is None, err
    return ProjectContext([ctx])


def _engine(src, path="opensim_tpu/server/fixture.py"):
    return dfm.DataflowEngine(_project(src, path))


MOD = "opensim_tpu.server.fixture"


# ---------------------------------------------------------------------------
# CFG + reaching definitions
# ---------------------------------------------------------------------------


def test_cfg_if_else_shape():
    src = textwrap.dedent(
        """
        def f(a):
            if a:
                x = 1
            else:
                x = 2
            return x
        """
    )
    fn = ast.parse(src).body[0]
    cfg = dfm.build_cfg(fn)
    # entry block must branch two ways and both arms rejoin before exit
    entry_succ = cfg.blocks[cfg.entry].succ
    assert len(entry_succ) == 2
    preds = cfg.preds()
    join = [b.id for b in cfg.blocks if len(preds[b.id]) == 2]
    assert join, "no join block for the if/else"


def test_cfg_while_has_back_edge():
    src = textwrap.dedent(
        """
        def f(n):
            i = 0
            while i < n:
                i = i + 1
            return i
        """
    )
    cfg = dfm.build_cfg(ast.parse(src).body[0])
    back = [
        (b.id, s)
        for b in cfg.blocks
        for s in b.succ
        if s < b.id  # an edge to an earlier block = the loop back edge
    ]
    assert back


def test_reaching_defs_join_over_branches():
    src = textwrap.dedent(
        """
        def f(a):
            x = 1
            if a:
                x = 2
            return x
        """
    )
    cfg = dfm.build_cfg(ast.parse(src).body[0])
    reach = cfg.reaching_defs()
    # at the block holding `return x`, both defs of x (lines 2 and 4) may reach
    ret_block = next(
        b.id
        for b in cfg.blocks
        if any(isinstance(a.node, ast.Return) for a in b.atoms)
    )
    assert reach[ret_block].get("x") == {3, 5}


def test_cfg_try_edges_into_handlers():
    src = textwrap.dedent(
        """
        def f(g):
            try:
                x = g()
            except ValueError:
                x = 0
            return x
        """
    )
    cfg = dfm.build_cfg(ast.parse(src).body[0])
    handler = next(
        b.id
        for b in cfg.blocks
        if any(a.role == "except" for a in b.atoms)
    )
    assert cfg.preds()[handler], "handler unreachable"


# ---------------------------------------------------------------------------
# function discovery: nested scopes
# ---------------------------------------------------------------------------


def test_units_include_nested_class_methods():
    eng = _engine(
        """
        def make_handler(server):
            class Handler:
                def do_GET(self):
                    return server

            return Handler
        """
    )
    assert f"{MOD}.make_handler.Handler.do_GET" in eng.units


def test_self_calls_resolve_inside_nested_classes():
    eng = _engine(
        """
        def make_handler():
            class Handler:
                def helper(self):
                    return 1

                def do_GET(self):
                    return self.helper()
        """
    )
    do_get = eng.units[f"{MOD}.make_handler.Handler.do_GET"]
    calls = list(eng._own_calls(do_get))
    assert eng.resolve_call(do_get, calls[0]) == f"{MOD}.make_handler.Handler.helper"


# ---------------------------------------------------------------------------
# effect inference
# ---------------------------------------------------------------------------


def test_direct_effects_by_kind():
    eng = _engine(
        """
        import os
        import random
        import time

        G = {}

        def clock():
            return time.monotonic()

        def rng():
            return random.random()

        def io():
            return open("/tmp/x")

        def sync(x):
            return x.item()

        def state(v):
            G["k"] = v

        def pure(a, b):
            return a + b
        """
    )
    kinds = {
        name: {e.kind for e in eng.direct_effects(f"{MOD}.{name}")}
        for name in ("clock", "rng", "io", "sync", "state", "pure")
    }
    assert kinds == {
        "clock": {"clock"},
        "rng": {"rng"},
        "io": {"io"},
        "sync": {"host-sync"},
        "state": {"state-write"},
        "pure": set(),
    }


def test_transitive_effects_fixpoint_through_recursion():
    eng = _engine(
        """
        import time

        def a(n):
            return b(n - 1) if n else 0

        def b(n):
            time.sleep(0.1)
            return a(n)
        """
    )
    eff = eng.transitive_effects(f"{MOD}.a")
    assert any(e.kind == "clock" for e in eff), "effect did not propagate through the cycle"
    assert eff[next(iter(eff))] == f"{MOD}.b"  # attributed to the direct site


def test_np_coercion_only_flags_parameters():
    eng = _engine(
        """
        import numpy as np

        def on_param(x):
            return np.asarray(x)

        def on_static():
            table = [1, 2, 3]
            return np.asarray(table)
        """
    )
    assert {e.kind for e in eng.direct_effects(f"{MOD}.on_param")} == {"host-sync"}
    assert eng.direct_effects(f"{MOD}.on_static") == ()


# ---------------------------------------------------------------------------
# jit regions
# ---------------------------------------------------------------------------


def test_jit_roots_decorator_partial_marker_and_scan_arg():
    eng = _engine(
        """
        import functools

        import jax

        @jax.jit
        def decorated(x):
            return x

        @functools.partial(jax.jit, static_argnames=("n",))
        def partial_decorated(x, n):
            return x

        def marked(x):  # opensim-lint: jit-region
            return x

        def body(c, x):
            return c, x

        def outer(xs):
            f = functools.partial(body)
            return jax.lax.scan(f, 0, xs)
        """
    )
    roots = eng.jit_roots()
    for name in ("decorated", "partial_decorated", "marked", "body"):
        assert f"{MOD}.{name}" in roots, (name, roots)
    assert f"{MOD}.outer" not in roots


def test_jit_reachability_crosses_call_graph():
    eng = _engine(
        """
        import jax

        def leaf(c):
            return c * 2

        def mid(c):
            return leaf(c)

        @jax.jit
        def root(x):
            return mid(x)

        def host(x):
            return leaf(x)
        """
    )
    reach = eng.jit_reachable()
    assert f"{MOD}.leaf" in reach and f"{MOD}.mid" in reach
    root, chain = reach[f"{MOD}.leaf"]
    assert root == f"{MOD}.root"
    assert chain == (f"{MOD}.root", f"{MOD}.mid")


def test_module_marker_promotes_every_function():
    eng = _engine(
        """
        # opensim-lint: jit-region-module
        def anything(x):
            return x
        """
    )
    assert f"{MOD}.anything" in eng.jit_roots()


# ---------------------------------------------------------------------------
# taint
# ---------------------------------------------------------------------------


def _hits(src, path="opensim_tpu/server/fixture.py"):
    return dfm.get_taint_hits(_project(src, path))


def test_taint_source_to_sink_intraprocedural():
    hits = _hits(
        """
        from urllib.parse import parse_qs

        def handler(q):
            name = parse_qs(q).get("f", [""])[-1]
            return open(name)
        """
    )
    assert len(hits) == 1
    assert hits[0].sink == "open()"
    assert "http-query" in hits[0].desc


def test_taint_is_flow_sensitive_about_sanitizers():
    # sanitize-then-open is clean; open-then-sanitize still fires
    clean = """
        from urllib.parse import parse_qs

        def sanitizer(fn):
            return fn

        @sanitizer
        def check(p):
            return p

        def handler(q):
            p = parse_qs(q).get("f", [""])[-1]
            p = check(p)
            return open(p)
        """
    assert _hits(clean) == []
    dirty = """
        from urllib.parse import parse_qs

        def sanitizer(fn):
            return fn

        @sanitizer
        def check(p):
            return p

        def handler(q):
            p = parse_qs(q).get("f", [""])[-1]
            fh = open(p)
            p = check(p)
            return fh
        """
    assert len(_hits(dirty)) == 1


def test_taint_interprocedural_param_to_sink():
    hits = _hits(
        """
        import sys

        def writer(path, data):
            with open(path, "w") as fh:
                fh.write(data)

        def main():
            writer(sys.argv[1], "x")
        """
    )
    assert len(hits) == 1
    assert "via writer()" in hits[0].desc and "cli-arg" in hits[0].desc


def test_taint_through_returns_and_coercions():
    hits = _hits(
        """
        def read_name(q):
            from urllib.parse import parse_qs

            return parse_qs(q).get("n", [""])[-1]

        def numeric(q):
            return int(read_name(q))  # coercion sanitizes

        def bad(q):
            return open(read_name(q))  # tainted return into sink

        def fine(q):
            return open("fixed-%d.log" % numeric(q))
        """
    )
    assert len(hits) == 1
    assert hits[0].unit.endswith(".bad")


def test_taint_yaml_documents():
    hits = _hits(
        """
        import yaml

        def load(path):
            doc = yaml.safe_load(open(path).read())
            return open(doc["include"])
        """
    )
    assert any("yaml-field" in h.desc for h in hits)


# ---------------------------------------------------------------------------
# tracer leaks
# ---------------------------------------------------------------------------


def test_tracer_leak_instance_and_module_state():
    leaks = dfm.get_tracer_leaks(
        _project(
            """
            import jax
            import jax.numpy as jnp

            _LAST = []

            class Rec:
                @jax.jit
                def step(self, x):
                    y = jnp.sum(x)
                    self.last = y
                    _LAST.append(x)
                    n = int(3)
                    self.gen = n        # concrete: clean
                    local = [y]
                    local.append(y)     # local container: clean
                    return y
            """
        )
    )
    sinks = sorted(h.sink for h in leaks)
    assert len(leaks) == 2
    assert any("self.last" in s for s in sinks)
    assert any("_LAST" in s for s in sinks)


# ---------------------------------------------------------------------------
# ABI parsers against the real abi-v5 sources
# ---------------------------------------------------------------------------


def test_abi_parsers_agree_on_real_sources():
    cc = open(os.path.join(REPO, "opensim_tpu/native/scan_engine.cc")).read()
    py = ast.parse(open(os.path.join(REPO, "opensim_tpu/native/__init__.py")).read())
    cc_fields, cc_problems = abi.parse_cc_struct(cc)
    py_fields, py_problems = abi.parse_py_layout(py)
    assert cc_problems == [] and py_problems == []
    assert len(cc_fields) == len(py_fields) > 100
    assert abi.compare_layouts(cc_fields, py_fields) == []
    assert abi.parse_cc_abi_version(cc) == abi.parse_py_abi_version(py) == 5


def test_abi_compare_names_the_drifted_field():
    cc = [("N", "i64"), ("R", "i64"), ("buf", "ptr:f32")]
    swapped = [("R", "i64"), ("N", "i64"), ("buf", "ptr:f32")]
    msgs = abi.compare_layouts(cc, swapped)
    assert msgs and "order drift" in msgs[0] and "`N`" in msgs[0]
    widened = [("N", "i64"), ("R", "i64"), ("buf", "ptr:f64")]
    msgs = abi.compare_layouts(cc, widened)
    assert msgs and "width drift" in msgs[0] and "`buf`" in msgs[0]
    missing = cc[:-1]
    msgs = abi.compare_layouts(cc, missing)
    assert any("count drift" in m for m in msgs)
    assert any("buf" in m for m in msgs)


def test_abi_serial_wire_parsers():
    cc = open(os.path.join(REPO, "opensim_tpu/native/serial_engine.cc")).read()
    py = ast.parse(open(os.path.join(REPO, "opensim_tpu/native/serial.py")).read())
    assert abi.parse_cc_serial_wire(cc) == (0x53524C31, 1)
    assert abi.parse_py_serial_wire(py) == (0x53524C31, 1)
