"""Decision-audit layer (ISSUE 7): reason registry, cross-engine
explanation parity, deep per-pod score breakdowns, REST surfaces, and the
decision counters in /metrics."""

import copy
import json
import os
import re
import threading
import urllib.request

import numpy as np
import pytest

from opensim_tpu.engine import explain as explain_mod
from opensim_tpu.engine import reasons
from opensim_tpu.engine.simulator import AppResource, simulate
from opensim_tpu.models import ResourceTypes, fixtures as fx
from opensim_tpu.ops import kernels
from opensim_tpu import native


needs_native = pytest.mark.skipif(
    not native.available(), reason=f"native engine unavailable: {native.load_error()}"
)


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

def small_cluster(n=6):
    rt = ResourceTypes()
    for i in range(n):
        rt.nodes.append(
            fx.make_fake_node(
                f"n{i:02d}", "4", "8Gi", "110",
                fx.with_labels(
                    {
                        "topology.kubernetes.io/zone": f"z{i % 2}",
                        "disk": "ssd" if i % 2 else "hdd",
                    }
                ),
            )
        )
    return rt


def mixed_apps():
    """Schedulable + unschedulable workloads covering fit/affinity/spread."""
    rt = ResourceTypes()
    rt.deployments.append(fx.make_fake_deployment("fits", 3, "500m", "1Gi"))
    rt.deployments.append(fx.make_fake_deployment("bigcpu", 2, "16", "1Gi"))
    rt.deployments.append(
        fx.make_fake_deployment(
            "ssd", 2, "100m", "128Mi", fx.with_node_selector({"disk": "ssd"})
        )
    )
    rt.deployments.append(fx.make_fake_deployment("bigmem", 1, "100m", "100Gi"))
    rt.deployments.append(
        fx.make_fake_deployment(
            "spread", 4, "100m", "64Mi",
            fx.with_topology_spread(
                [
                    {
                        "maxSkew": 1,
                        "topologyKey": "topology.kubernetes.io/zone",
                        "whenUnsatisfiable": "DoNotSchedule",
                        "labelSelector": {"matchLabels": {"app": "spread"}},
                    }
                ]
            ),
        )
    )
    return [AppResource("t", rt)]


def canon_name(pod_name: str) -> str:
    """Pod names embed globally-counted uids assigned at expansion time, so
    two simulate() runs name the same logical pod differently — strip the
    hex-uid segments before cross-run comparison."""
    return re.sub(r"-[0-9a-f]{10}", "", pod_name)


def canon(e_dict: dict) -> dict:
    d = dict(e_dict)
    if "pod" in d:
        d["pod"] = canon_name(d["pod"])
    return d


def run_engine(cluster, apps, engine, explain=True, **kw):
    """One simulate on a forced engine, on deep copies so repeated runs see
    identical inputs (pod names included — uids are stamped at build)."""
    env = {"native": {"OPENSIM_NATIVE": "1"}, "xla": {"OPENSIM_DISABLE_NATIVE": "1"}}[engine]
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        return simulate(copy.deepcopy(cluster), copy.deepcopy(apps), explain=explain, **kw)
    finally:
        for k, v in saved.items():
            os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)


# ---------------------------------------------------------------------------
# the registered reason-code enum
# ---------------------------------------------------------------------------

def test_reason_enum_aligns_with_kernel_filter_indices():
    assert reasons.Reason.NODE_PIN.value == kernels.F_NODE_PIN
    assert reasons.Reason.UNSCHEDULABLE.value == kernels.F_UNSCHEDULABLE
    assert reasons.Reason.TAINT.value == kernels.F_TAINT
    assert reasons.Reason.AFFINITY.value == kernels.F_AFFINITY
    assert reasons.Reason.PORTS.value == kernels.F_PORTS
    assert reasons.Reason.FIT.value == kernels.F_FIT
    assert reasons.Reason.SPREAD.value == kernels.F_SPREAD
    assert reasons.Reason.INTERPOD.value == kernels.F_INTERPOD
    assert reasons.Reason.GPU.value == kernels.F_GPU
    assert reasons.Reason.LOCAL.value == kernels.F_LOCAL
    assert reasons.Reason.EXTRA.value == kernels.F_EXTRA
    assert len(reasons.FILTER_MESSAGES) == kernels.NUM_FILTERS
    # kernels.FILTER_REASONS is the registry's table, not a second copy
    assert kernels.FILTER_REASONS is reasons.FILTER_MESSAGES


def test_render_unschedulable_kube_phrasing():
    counts = [
        reasons.ReasonCount(reasons.Reason.TAINT, 3),
        reasons.ReasonCount(reasons.Reason.FIT, 1, resource="cpu"),
    ]
    msg = reasons.render_unschedulable(4, counts)
    assert msg == (
        "0/4 nodes are available: 1 Insufficient cpu, "
        "3 node(s) had taints that the pod didn't tolerate."
    )
    assert reasons.render_unschedulable(7, []) == "0/7 nodes are available."


def test_reason_helpers_format():
    assert reasons.node_not_found("gone-01") == 'node "gone-01" not found'
    assert reasons.preempted("ns", "hi") == "preempted by higher-priority pod ns/hi"
    assert "no scheduler profile named 'x'" in reasons.unknown_profile("x")


def test_primary_code_precedence():
    counts = [
        reasons.ReasonCount(reasons.Reason.FIT, 2, resource="cpu"),
        reasons.ReasonCount(reasons.Reason.TAINT, 2),
        reasons.ReasonCount(reasons.Reason.SPREAD, 5),
    ]
    assert reasons.primary_code(counts) is reasons.Reason.SPREAD
    # tie between TAINT(2) and FIT(2): lower filter index wins
    assert reasons.primary_code(counts[:2]) is reasons.Reason.TAINT
    assert reasons.primary_code([]) is None


# ---------------------------------------------------------------------------
# cross-engine explanation parity
# ---------------------------------------------------------------------------

@needs_native
def test_explanations_identical_between_engines():
    cluster, apps = small_cluster(), mixed_apps()
    rn = run_engine(cluster, apps, "native")
    rx = run_engine(cluster, apps, "xla")
    assert rn.engine.name == "native" and rn.engine.native_path == "generic"
    assert rx.engine.name == "xla"
    assert rn.engine.filter_rejects == rx.engine.filter_rejects
    en, ex = rn.engine.explanations, rx.engine.explanations
    assert len(en) == len(ex) == len(rn.engine.explain_ctx.prep.ordered)
    for a, b in zip(en, ex):
        assert canon(a.to_dict()) == canon(b.to_dict())
    # the audit found the infeasible workloads with kube phrasing
    msgs = [e.message for e in en if e.status == "unschedulable"]
    assert any("Insufficient cpu" in m for m in msgs)
    assert any("Insufficient memory" in m for m in msgs)
    assert all(m.startswith("0/6 nodes are available") for m in msgs)


@needs_native
def test_native_in_engine_rejects_match_row_derivation():
    """The C++ engine's ScanArgs.filter_rejects accumulator (abi v4) must
    equal the aggregation of its own per-pod attribution rows."""
    r = run_engine(small_cluster(), mixed_apps(), "native")
    ctx = r.engine.explain_ctx
    mask = ctx.prep.forced.copy()
    mask = ~mask  # every unforced pod is valid in this stream
    derived = explain_mod.audit_rejects(
        ctx.static_fail, ctx.sf_rows, ctx.fail_counts, mask
    )
    assert r.engine.filter_rejects == reasons.rejects_dict(derived)


@needs_native
def test_explain_disabled_is_unchanged_and_attaches_nothing():
    cluster, apps = small_cluster(), mixed_apps()
    r0 = run_engine(cluster, apps, "native", explain=False)
    r1 = run_engine(cluster, apps, "native", explain=True)
    assert r0.engine.explanations is None
    assert r0.engine.filter_rejects is None
    assert r0.engine.explain_ctx is None
    # explain=1 forces the generic path but placements are bit-identical
    assert r0.engine.native_path in ("incremental", "generic", "mixed")
    placements0 = {
        ns.node.metadata.name: sorted(canon_name(p.metadata.name) for p in ns.pods)
        for ns in r0.node_status
    }
    placements1 = {
        ns.node.metadata.name: sorted(canon_name(p.metadata.name) for p in ns.pods)
        for ns in r1.node_status
    }
    assert placements0 == placements1
    assert [u.reason for u in r0.unscheduled_pods] == [
        u.reason for u in r1.unscheduled_pods
    ]


@needs_native
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_reason_parity_fuzz(seed):
    """ISSUE 7 satellite: random cluster + workload; XLA and C++ generic
    explanations agree pod-for-pod (reasons, counts, messages, winners)."""
    rng = np.random.default_rng(seed)
    rt = ResourceTypes()
    zones = [f"z{k}" for k in range(int(rng.integers(1, 4)))]
    n_nodes = int(rng.integers(3, 9))
    for i in range(n_nodes):
        opts = [
            fx.with_labels(
                {
                    "topology.kubernetes.io/zone": str(rng.choice(zones)),
                    "tier": str(rng.choice(["web", "db", "cache"])),
                }
            )
        ]
        if rng.random() < 0.3:
            opts.append(
                fx.with_taints(
                    [{"key": "dedicated", "value": "infra", "effect": "NoSchedule"}]
                )
            )
        rt.nodes.append(
            fx.make_fake_node(
                f"fz{i:02d}",
                str(int(rng.integers(2, 9))),
                f"{int(rng.integers(4, 17))}Gi",
                "110",
                *opts,
            )
        )
    n_workloads = int(rng.integers(2, 6))
    for w in range(n_workloads):
        opts = []
        if rng.random() < 0.4:
            opts.append(fx.with_node_selector({"tier": str(rng.choice(["web", "db", "gone"]))}))
        if rng.random() < 0.4:
            opts.append(
                fx.with_topology_spread(
                    [
                        {
                            "maxSkew": int(rng.integers(1, 3)),
                            "topologyKey": "topology.kubernetes.io/zone",
                            "whenUnsatisfiable": str(
                                rng.choice(["DoNotSchedule", "ScheduleAnyway"])
                            ),
                            "labelSelector": {"matchLabels": {"app": f"fz-{w}"}},
                        }
                    ]
                )
            )
        cpu = str(rng.choice(["100m", "500m", "2", "12"]))
        mem = str(rng.choice(["128Mi", "1Gi", "4Gi", "64Gi"]))
        rt.deployments.append(
            fx.make_fake_deployment(f"fz-{w}", int(rng.integers(1, 5)), cpu, mem, *opts)
        )
    cluster = ResourceTypes()
    cluster.nodes = rt.nodes
    apps_rt = ResourceTypes()
    apps_rt.deployments = rt.deployments
    apps = [AppResource("fuzz", apps_rt)]

    rn = run_engine(cluster, apps, "native")
    rx = run_engine(cluster, apps, "xla")
    assert rn.engine.filter_rejects == rx.engine.filter_rejects
    for a, b in zip(rn.engine.explanations, rx.engine.explanations):
        assert canon(a.to_dict()) == canon(b.to_dict())
    # per-pod attribution rows agree wherever the pod was audited
    cn, cx = rn.engine.explain_ctx, rx.engine.explain_ctx
    unforced = ~cn.prep.forced
    np.testing.assert_array_equal(
        cn.fail_counts[unforced], cx.fail_counts[unforced]
    )
    np.testing.assert_array_equal(
        cn.insufficient[unforced], cx.insufficient[unforced]
    )


# ---------------------------------------------------------------------------
# deep per-pod audit
# ---------------------------------------------------------------------------

@needs_native
def test_deep_explain_scheduled_pod_breakdown():
    r = run_engine(small_cluster(), mixed_apps(), "native")
    ctx = r.engine.explain_ctx
    scheduled = [
        i for i, e in enumerate(r.engine.explanations)
        if e.status == "scheduled" and not e.forced
    ]
    assert scheduled
    for i in scheduled[:3]:
        deep = explain_mod.explain_pod(ctx, i)
        assert deep.status == "scheduled"
        assert deep.node == r.engine.explanations[i].node
        assert deep.scores and deep.score is not None
        # the breakdown sums to the reported total (same accumulation order)
        assert abs(sum(deep.scores.values()) - deep.score) < 1e-2
        if deep.runner_up is not None:
            assert deep.runner_up != deep.node
            assert deep.margin is not None and deep.margin >= 0.0


@needs_native
def test_deep_explain_unschedulable_and_engines_agree():
    cluster, apps = small_cluster(), mixed_apps()
    rn = run_engine(cluster, apps, "native")
    rx = run_engine(cluster, apps, "xla")
    for r in (rn, rx):
        ctx = r.engine.explain_ctx
        bad = [i for i, e in enumerate(r.engine.explanations) if e.status == "unschedulable"]
        assert bad
        deep = explain_mod.explain_pod(ctx, bad[0])
        assert deep.reasons and deep.message.startswith("0/6 nodes are available")
    dn = explain_mod.explain_pod(rn.engine.explain_ctx, 0)
    dx = explain_mod.explain_pod(rx.engine.explain_ctx, 0)
    assert canon(dn.to_dict()) == canon(dx.to_dict())


def test_deep_explain_forced_pod():
    cluster = small_cluster()
    cluster.pods.append(
        fx.make_fake_pod("pinned", "100m", "64Mi", fx.with_node_name("n03"))
    )
    cluster.pods.append(
        fx.make_fake_pod("orphan", "100m", "64Mi", fx.with_node_name("no-such-node"))
    )
    r = run_engine(cluster, mixed_apps(), "xla")
    ctx = r.engine.explain_ctx
    i = ctx.index_of("default/pinned")
    deep = explain_mod.explain_pod(ctx, i)
    assert deep.status == "scheduled" and deep.forced and deep.node == "n03"
    j = ctx.index_of("default/orphan")
    deep = explain_mod.explain_pod(ctx, j)
    assert deep.status == "unschedulable"
    assert deep.message == 'node "no-such-node" not found'
    assert any(u.reason == deep.message for u in r.unscheduled_pods)


def test_explain_ctx_index_of_ambiguity():
    r = run_engine(small_cluster(), mixed_apps(), "xla")
    ctx = r.engine.explain_ctx
    full = f"{ctx.prep.ordered[0].metadata.namespace}/{ctx.prep.ordered[0].metadata.name}"
    assert ctx.index_of(full) == 0
    assert ctx.index_of("nope/nothing") is None


# ---------------------------------------------------------------------------
# decision counters
# ---------------------------------------------------------------------------

def test_simulate_bumps_decision_counters():
    from opensim_tpu.obs.metrics import RECORDER

    RECORDER.reset()
    run_engine(small_cluster(), mixed_apps(), "xla", explain=False)
    lines = "\n".join(RECORDER.render_lines())
    assert 'simon_unschedulable_total{reason="fit"}' in lines
    assert 'simon_filter_reject_total{filter="fit"}' in lines
    assert "# HELP simon_unschedulable_total" in lines
    assert "# TYPE simon_filter_reject_total counter" in lines
    RECORDER.reset()


def test_schedule_span_carries_reason_events():
    from opensim_tpu.obs import trace as tracing

    tr = tracing.start_trace("test-explain", force=True)
    with tracing.trace_scope(tr):
        run_engine(small_cluster(), mixed_apps(), "xla", explain=False)
    tr.finish()
    names = [sp.name for sp in tr.walk()]
    assert "placement.reasons" in names
    assert "placement.unschedulable" in names
    agg = next(sp for sp in tr.walk() if sp.name == "placement.reasons")
    assert agg.attrs["unschedulable"] >= 3
    assert agg.attrs.get("reason_fit", 0) >= 1
    ev = next(sp for sp in tr.walk() if sp.name == "placement.unschedulable")
    assert "0/6 nodes are available" in ev.attrs["reason"]


# ---------------------------------------------------------------------------
# REST surfaces
# ---------------------------------------------------------------------------

def _rest_server():
    from http.server import ThreadingHTTPServer

    from opensim_tpu.server.rest import SimonServer, make_handler

    server = SimonServer(base_cluster=small_cluster())
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(server))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def _post(base, path, payload, headers=None):
    req = urllib.request.Request(
        f"{base}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, dict(resp.headers), json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def test_rest_explain_flag_and_placements_endpoint():
    httpd, base = _rest_server()
    try:
        payload = {
            "deployments": [
                fx.make_fake_deployment("ok", 2, "100m", "128Mi").raw,
                fx.make_fake_deployment("nope", 1, "64", "1Gi").raw,
            ]
        }
        rid = "explain-rest-1"
        code, headers, body = _post(
            base, "/api/deploy-apps?explain=1", payload,
            {"X-Simon-Request-Id": rid},
        )
        assert code == 200
        assert headers.get("X-Simon-Request-Id") == rid
        bad = [u for u in body["unscheduledPods"] if "nope" in u["pod"]]
        assert bad and "explanation" in bad[0]
        exp = bad[0]["explanation"]
        assert exp["status"] == "unschedulable"
        assert any(c["code"] == "fit" for c in exp["reasons"])
        assert body["filterRejects"].get("fit", 0) >= 1

        with urllib.request.urlopen(
            f"{base}/api/debug/placements/{rid}", timeout=30
        ) as resp:
            audit = json.loads(resp.read())
        assert audit["request_id"] == rid
        assert audit["pods_total"] == 3
        assert audit["truncated"] == 0
        # unschedulable records rank first in the stored audit
        assert audit["explanations"][0]["status"] == "unschedulable"
        assert audit["filter_rejects"].get("fit", 0) >= 1

        # a request WITHOUT explain=1 records no placements
        code, headers2, _ = _post(base, "/api/deploy-apps", payload)
        rid2 = headers2.get("X-Simon-Request-Id")
        try:
            with urllib.request.urlopen(
                f"{base}/api/debug/placements/{rid2}", timeout=30
            ) as resp:
                assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
            assert "explain=1" in json.loads(e.read())["hint"]
    finally:
        httpd.shutdown()


def test_request_id_on_get_requests_and_access_log(monkeypatch, caplog):
    """ISSUE 7 satellite: every request — GETs included — gets a request id
    that shows up in the response header and the JSON access log, so logs
    join against the flight recorder without scraping anything."""
    import logging

    monkeypatch.setenv("OPENSIM_ACCESS_LOG", "1")
    httpd, base = _rest_server()
    try:
        with caplog.at_level(logging.INFO, logger="opensim_tpu.access"):
            req = urllib.request.Request(
                f"{base}/metrics", headers={"X-Simon-Request-Id": "get-join-1"}
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.headers.get("X-Simon-Request-Id") == "get-join-1"
            with urllib.request.urlopen(f"{base}/healthz", timeout=30) as resp:
                assert resp.headers.get("X-Simon-Request-Id")
        entries = [json.loads(r.message) for r in caplog.records]
        assert all(e["request_id"] for e in entries)
        assert any(e["request_id"] == "get-join-1" for e in entries)
    finally:
        httpd.shutdown()


def test_explanations_exclude_dropped_pods():
    """Regression: drop_pods-masked pods (scale-apps cached path, live-twin
    DELETEDs) must not appear in the audit as phantom unschedulable pods."""
    from opensim_tpu.engine.simulator import prepare

    cluster, apps = small_cluster(), mixed_apps()
    cl, ap = copy.deepcopy(cluster), copy.deepcopy(apps)
    prep = prepare(cl, ap)
    drop = np.zeros(len(prep.ordered), dtype=bool)
    drop[0] = True
    dropped_name = (
        f"{prep.ordered[0].metadata.namespace}/{prep.ordered[0].metadata.name}"
    )
    r = simulate(cl, ap, prep=prep, drop_pods=drop, explain=True)
    names = [e.pod for e in r.engine.explanations]
    assert dropped_name not in names
    assert len(names) == len(prep.ordered) - 1


def test_rest_explain_with_no_schedulable_pods():
    """Regression: explain=1 against a pod-free snapshot (engine=None) must
    stay a 200, and the placements endpoint 404s cleanly."""
    httpd, base = _rest_server()
    try:
        code, headers, body = _post(
            base, "/api/deploy-apps?explain=1", {"deployments": []},
            {"X-Simon-Request-Id": "explain-empty-1"},
        )
        assert code == 200, body
        assert body["unscheduledPods"] == []
        try:
            with urllib.request.urlopen(
                f"{base}/api/debug/placements/explain-empty-1", timeout=30
            ):
                assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        httpd.shutdown()
