"""Unit tests for the host object model — the unit layer the reference lacks
(SURVEY.md §4: only one integration test exists upstream)."""

import json
import os

import pytest

from opensim_tpu.models import (
    ANNO_POD_LOCAL_STORAGE,
    ANNO_WORKLOAD_KIND,
    Node,
    Pod,
    parse_quantity,
    parse_quantity_milli,
)
from opensim_tpu.models import expand, fixtures, selectors


def test_parse_quantity():
    assert parse_quantity("1500m") == 1.5
    assert parse_quantity_milli("1500m") == 1500
    assert parse_quantity("2") == 2.0
    assert parse_quantity("1Gi") == 1024**3
    assert parse_quantity("61255492Ki") == 61255492 * 1024
    assert parse_quantity("1k") == 1000
    assert parse_quantity("0") == 0
    assert parse_quantity(None) == 0
    assert parse_quantity("1e3") == 1000
    with pytest.raises(ValueError):
        parse_quantity("banana")


def test_pod_requests_max_of_init_containers():
    pod = Pod.from_dict(
        {
            "kind": "Pod",
            "metadata": {"name": "p"},
            "spec": {
                "containers": [
                    {"name": "a", "resources": {"requests": {"cpu": "100m", "memory": "1Gi"}}},
                    {"name": "b", "resources": {"requests": {"cpu": "200m"}}},
                ],
                "initContainers": [
                    {"name": "init", "resources": {"requests": {"cpu": "1", "memory": "512Mi"}}}
                ],
            },
        }
    )
    req = pod.resource_requests()
    assert req["cpu"] == 1.0  # init container dominates 0.3
    assert req["memory"] == 1024**3


def test_deployment_expansion_names_and_owners():
    deploy = fixtures.make_fake_deployment("web", replicas=3)
    pods = expand.pods_from_deployment(deploy)
    assert len(pods) == 3
    for p in pods:
        assert p.metadata.name.startswith("web-")
        assert p.metadata.owner_references[0].kind == "ReplicaSet"
        assert p.metadata.annotations[ANNO_WORKLOAD_KIND] == "ReplicaSet"
        assert p.spec.scheduler_name == "default-scheduler"
    # All pods share one generated ReplicaSet owner.
    assert len({p.metadata.owner_references[0].name for p in pods}) == 1


def test_statefulset_ordinal_names_and_storage_annotation():
    sts = fixtures.make_fake_stateful_set("db", replicas=2)
    sts.volume_claim_templates = [
        {
            "metadata": {"name": "data"},
            "spec": {
                "storageClassName": "open-local-lvm",
                "resources": {"requests": {"storage": "10Gi"}},
            },
        }
    ]
    pods = expand.pods_from_stateful_set(sts)
    assert [p.metadata.name for p in pods] == ["db-0", "db-1"]
    vols = json.loads(pods[0].metadata.annotations[ANNO_POD_LOCAL_STORAGE])
    assert vols["volumes"][0]["kind"] == "LVM"
    assert vols["volumes"][0]["size"] == str(10 * 1024**3)


def test_daemonset_expansion_respects_taints_and_selector():
    ds = fixtures.make_fake_daemon_set("agent")
    tainted = fixtures.make_fake_node(
        "tainted", "4", "8Gi", "110", fixtures.with_taints([{"key": "dedicated", "value": "x", "effect": "NoSchedule"}])
    )
    normal = fixtures.make_fake_node("normal")
    pods = expand.pods_from_daemon_set(ds, [tainted, normal])
    assert len(pods) == 1
    # the daemon pod is pinned by matchFields node affinity, not nodeName
    aff = pods[0].spec.affinity["nodeAffinity"]["requiredDuringSchedulingIgnoredDuringExecution"]
    assert aff["nodeSelectorTerms"][0]["matchFields"][0]["values"] == ["normal"]

    tolerant = fixtures.make_fake_daemon_set(
        "agent2", "100m", "128Mi", fixtures.with_tolerations([{"operator": "Exists"}])
    )
    pods = expand.pods_from_daemon_set(tolerant, [tainted, normal])
    assert len(pods) == 2


def test_cronjob_expansion():
    cj = fixtures.make_fake_cron_job("tick", completions=2)
    pods = expand.pods_from_cron_job(cj)
    assert len(pods) == 2
    assert pods[0].metadata.annotations[ANNO_WORKLOAD_KIND] == "Job"


# ---------------------------------------------------------------------------
# workload-expansion proto cache (ISSUE 16)
# ---------------------------------------------------------------------------


def _expansion_canon(pod, name):
    """Pod content with the volatile bits (uids, rand suffixes) normalized
    onto the workload name, for cache-on vs cache-off comparison."""
    m = pod.metadata

    def n(s):
        return "NAME" if isinstance(s, str) and name in s else s

    return {
        "ns": m.namespace,
        "labels": dict(m.labels),
        "annotations": {k: n(v) for k, v in m.annotations.items()},
        "generate_name": n(m.generate_name),
        "owners": [(r.kind, n(r.name), r.api_version, r.controller) for r in m.owner_references],
        "requests": pod.resource_requests(),
        "scheduler": pod.spec.scheduler_name,
        "volumes": pod.spec.volumes,
        "phase": pod.phase,
        "raw_spec": pod.raw.get("spec"),
    }


@pytest.mark.parametrize("kind", ["Deployment", "ReplicaSet", "StatefulSet", "Job", "CronJob"])
def test_expand_cache_hit_is_bitidentical(kind, monkeypatch):
    """A cache hit materializes pods identical (modulo uids/rand suffixes)
    to a cold build, for every cached workload kind — including a hit
    under a DIFFERENT workload name, which must be rewritten completely
    (no cached name may leak into the materialized pods)."""
    makers = {
        "Deployment": (fixtures.make_fake_deployment, expand.pods_from_deployment),
        "ReplicaSet": (fixtures.make_fake_replica_set, expand.pods_from_replica_set),
        "StatefulSet": (fixtures.make_fake_stateful_set, expand.pods_from_stateful_set),
        "Job": (lambda n, **kw: fixtures.make_fake_job(n, completions=3), expand.pods_from_job),
        "CronJob": (lambda n, **kw: fixtures.make_fake_cron_job(n, completions=3), expand.pods_from_cron_job),
    }
    make, expander = makers[kind]
    expand.expand_cache_clear()
    monkeypatch.setenv("OPENSIM_EXPAND_CACHE", "0")
    cold = expander(make("alpha", replicas=3))
    monkeypatch.setenv("OPENSIM_EXPAND_CACHE", "1")
    expander(make("alpha", replicas=3))  # miss populates
    warm = expander(make("alpha", replicas=3))  # hit materializes
    other = expander(make("beta", replicas=3))  # hit, different name
    stats = expand.expand_cache_stats()
    assert stats["hits"] == 2 and stats["misses"] == 1, stats
    assert [_expansion_canon(p, "alpha") for p in warm] == [
        _expansion_canon(p, "alpha") for p in cold
    ]
    for p in other:
        blob = json.dumps(
            {
                "name": p.metadata.name,
                "generate_name": p.metadata.generate_name,
                "annotations": p.metadata.annotations,
                "labels": p.metadata.labels,
                "owners": [r.name for r in p.metadata.owner_references],
            }
        )
        assert "alpha" not in blob, blob
        assert "beta" in blob, blob
    # expansions never repeat pod names (fresh rand suffixes per hit) —
    # StatefulSets excepted: their ordinal names are deterministic by design
    if kind != "StatefulSet":
        names = [p.metadata.name for p in cold + warm + other]
        assert len(names) == len(set(names)), names


def test_expand_cache_entry_survives_caller_mutation(monkeypatch):
    """Callers mutate returned pods (bind decode writes node_name and GPU
    annotations): the cached proto must stay pristine, so a later hit
    starts clean."""
    monkeypatch.setenv("OPENSIM_EXPAND_CACHE", "1")
    expand.expand_cache_clear()
    first = expand.pods_from_deployment(fixtures.make_fake_deployment("mut", replicas=2))
    for p in first:
        p.spec.node_name = "node-x"
        p.metadata.annotations["poison"] = "1"
        p.metadata.labels["poison"] = "1"
    again = expand.pods_from_deployment(fixtures.make_fake_deployment("mut", replicas=2))
    assert expand.expand_cache_stats()["hits"] == 1
    for p in again:
        assert p.spec.node_name == ""
        assert "poison" not in p.metadata.annotations
        assert "poison" not in p.metadata.labels


def test_expand_cache_distinct_content_never_shares(monkeypatch):
    """Same name, different template content → distinct entries; the knob
    off bypasses the cache entirely."""
    monkeypatch.setenv("OPENSIM_EXPAND_CACHE", "1")
    expand.expand_cache_clear()
    small = expand.pods_from_deployment(fixtures.make_fake_deployment("w", 2, "100m", "128Mi"))
    big = expand.pods_from_deployment(fixtures.make_fake_deployment("w", 2, "4", "8Gi"))
    assert expand.expand_cache_stats()["misses"] == 2
    assert small[0].resource_requests() != big[0].resource_requests()
    monkeypatch.setenv("OPENSIM_EXPAND_CACHE", "0")
    expand.pods_from_deployment(fixtures.make_fake_deployment("w", 2, "100m", "128Mi"))
    assert expand.expand_cache_stats()["hits"] == 0


def test_expand_cache_keys_parsed_spec_mutations(monkeypatch):
    """Post-parse mutation of the PARSED template_spec (how tests and
    callers select a scheduler profile) must diverge the key even though
    the raw dict is unchanged — the proto is built from the parsed
    object, so a raw-only key would hand the mutated workload another
    workload's unmutated expansion (regression: segmented multi-profile
    streams silently collapsed to one profile)."""
    monkeypatch.setenv("OPENSIM_EXPAND_CACHE", "1")
    expand.expand_cache_clear()
    plain = fixtures.make_fake_deployment("lane-a", replicas=2)
    packer = fixtures.make_fake_deployment("lane-b", replicas=2)
    packer.template_spec.scheduler_name = "packer"
    expand.pods_from_deployment(plain)
    pods = expand.pods_from_deployment(packer)
    assert all(p.spec.scheduler_name == "packer" for p in pods)
    assert expand.expand_cache_stats()["misses"] == 2


def test_make_valid_pod_sanitization():
    pod = Pod.from_dict(
        {
            "kind": "Pod",
            "metadata": {"name": "p"},
            "spec": {
                "containers": [
                    {
                        "name": "c",
                        "env": [{"name": "A", "value": "B"}],
                        "volumeMounts": [{"name": "v", "mountPath": "/x"}],
                        "livenessProbe": {"exec": {"command": ["true"]}},
                    }
                ],
                "volumes": [{"name": "v", "persistentVolumeClaim": {"claimName": "c"}}],
            },
        }
    )
    valid = expand.make_valid_pod(pod)
    assert valid.metadata.namespace == "default"
    c = valid.raw["spec"]["containers"][0]
    assert "env" not in c and "volumeMounts" not in c and "livenessProbe" not in c
    assert valid.raw["spec"]["volumes"][0]["hostPath"]["path"] == "/tmp"
    assert "persistentVolumeClaim" not in valid.raw["spec"]["volumes"][0]


def test_selector_matching():
    node = fixtures.make_fake_node("n1", "4", "8Gi", "110", fixtures.with_labels({"disk": "ssd", "zone": "a"}))
    assert selectors.match_label_selector({"matchLabels": {"disk": "ssd"}}, node.metadata.labels)
    assert not selectors.match_label_selector(None, node.metadata.labels)
    assert selectors.match_label_selector({}, node.metadata.labels)  # empty matches all
    assert selectors.match_label_selector(
        {"matchExpressions": [{"key": "disk", "operator": "In", "values": ["ssd", "hdd"]}]},
        node.metadata.labels,
    )
    assert selectors.match_label_selector(
        {"matchExpressions": [{"key": "gpu", "operator": "DoesNotExist"}]}, node.metadata.labels
    )
    term = {"matchExpressions": [{"key": "zone", "operator": "NotIn", "values": ["b"]}]}
    assert selectors.match_node_selector_term(term, node)
    assert not selectors.match_node_selector_term({}, node)  # empty term matches nothing


def test_taint_toleration():
    from opensim_tpu.models import Taint, Toleration

    taint = Taint(key="k", value="v", effect="NoSchedule")
    assert selectors.toleration_tolerates_taint(Toleration(key="k", operator="Exists"), taint)
    assert selectors.toleration_tolerates_taint(Toleration(key="k", operator="Equal", value="v"), taint)
    assert not selectors.toleration_tolerates_taint(Toleration(key="k", operator="Equal", value="w"), taint)
    assert selectors.toleration_tolerates_taint(Toleration(operator="Exists"), taint)
    assert not selectors.toleration_tolerates_taint(
        Toleration(key="k", operator="Exists", effect="NoExecute"), taint
    )
    assert selectors.find_untolerated_taint([taint], []) is taint
    assert selectors.find_untolerated_taint([taint], [Toleration(operator="Exists")]) is None


def test_load_repo_examples():
    rt = expand.load_cluster_from_dir("example/cluster/demo")
    assert len(rt.nodes) == 4
    assert any("simon/node-local-storage" in n.metadata.annotations for n in rt.nodes)
    app, skipped = expand.resources_from_dicts(expand.load_yaml_objects("example/application/simple"))
    pods = expand.generate_pods_from_resources(app, rt.nodes)
    # 1 bare pod + 3 deployment + 2 replicaset + 2 job + 6 sts + 2 daemonset
    # (the exporter DS tolerates no control-plane taint → workers only)
    assert len(pods) == 16


@pytest.mark.skipif(
    not os.path.isdir("/root/reference/example"), reason="reference checkout not mounted"
)
def test_load_reference_examples():
    rt = expand.load_cluster_from_dir("/root/reference/example/cluster/demo_1")
    assert len(rt.nodes) == 4
    assert any("simon/node-local-storage" in n.metadata.annotations for n in rt.nodes)
    app, skipped = expand.resources_from_dicts(
        expand.load_yaml_objects("/root/reference/example/application/simple")
    )
    pods = expand.generate_pods_from_resources(app, rt.nodes)
    # 1 bare pod + 4 deployment + 2 replicaset + 2 job + 5 sts + 3 daemonset (all nodes tolerated)
    assert len(pods) == 17


def test_touch_bumps_global_epoch_thread_safely():
    import threading

    from opensim_tpu.models.objects import Pod, touch_epoch

    pods = [Pod() for _ in range(8)]
    before = touch_epoch()

    def hammer(p):
        for _ in range(500):
            p.touch()

    threads = [threading.Thread(target=hammer, args=(p,)) for p in pods]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert touch_epoch() - before == 8 * 500  # no lost increments
    assert all(p.local_version == 500 for p in pods)
