"""Unit tests for the host object model — the unit layer the reference lacks
(SURVEY.md §4: only one integration test exists upstream)."""

import json
import os

import pytest

from opensim_tpu.models import (
    ANNO_POD_LOCAL_STORAGE,
    ANNO_WORKLOAD_KIND,
    Node,
    Pod,
    parse_quantity,
    parse_quantity_milli,
)
from opensim_tpu.models import expand, fixtures, selectors


def test_parse_quantity():
    assert parse_quantity("1500m") == 1.5
    assert parse_quantity_milli("1500m") == 1500
    assert parse_quantity("2") == 2.0
    assert parse_quantity("1Gi") == 1024**3
    assert parse_quantity("61255492Ki") == 61255492 * 1024
    assert parse_quantity("1k") == 1000
    assert parse_quantity("0") == 0
    assert parse_quantity(None) == 0
    assert parse_quantity("1e3") == 1000
    with pytest.raises(ValueError):
        parse_quantity("banana")


def test_pod_requests_max_of_init_containers():
    pod = Pod.from_dict(
        {
            "kind": "Pod",
            "metadata": {"name": "p"},
            "spec": {
                "containers": [
                    {"name": "a", "resources": {"requests": {"cpu": "100m", "memory": "1Gi"}}},
                    {"name": "b", "resources": {"requests": {"cpu": "200m"}}},
                ],
                "initContainers": [
                    {"name": "init", "resources": {"requests": {"cpu": "1", "memory": "512Mi"}}}
                ],
            },
        }
    )
    req = pod.resource_requests()
    assert req["cpu"] == 1.0  # init container dominates 0.3
    assert req["memory"] == 1024**3


def test_deployment_expansion_names_and_owners():
    deploy = fixtures.make_fake_deployment("web", replicas=3)
    pods = expand.pods_from_deployment(deploy)
    assert len(pods) == 3
    for p in pods:
        assert p.metadata.name.startswith("web-")
        assert p.metadata.owner_references[0].kind == "ReplicaSet"
        assert p.metadata.annotations[ANNO_WORKLOAD_KIND] == "ReplicaSet"
        assert p.spec.scheduler_name == "default-scheduler"
    # All pods share one generated ReplicaSet owner.
    assert len({p.metadata.owner_references[0].name for p in pods}) == 1


def test_statefulset_ordinal_names_and_storage_annotation():
    sts = fixtures.make_fake_stateful_set("db", replicas=2)
    sts.volume_claim_templates = [
        {
            "metadata": {"name": "data"},
            "spec": {
                "storageClassName": "open-local-lvm",
                "resources": {"requests": {"storage": "10Gi"}},
            },
        }
    ]
    pods = expand.pods_from_stateful_set(sts)
    assert [p.metadata.name for p in pods] == ["db-0", "db-1"]
    vols = json.loads(pods[0].metadata.annotations[ANNO_POD_LOCAL_STORAGE])
    assert vols["volumes"][0]["kind"] == "LVM"
    assert vols["volumes"][0]["size"] == str(10 * 1024**3)


def test_daemonset_expansion_respects_taints_and_selector():
    ds = fixtures.make_fake_daemon_set("agent")
    tainted = fixtures.make_fake_node(
        "tainted", "4", "8Gi", "110", fixtures.with_taints([{"key": "dedicated", "value": "x", "effect": "NoSchedule"}])
    )
    normal = fixtures.make_fake_node("normal")
    pods = expand.pods_from_daemon_set(ds, [tainted, normal])
    assert len(pods) == 1
    # the daemon pod is pinned by matchFields node affinity, not nodeName
    aff = pods[0].spec.affinity["nodeAffinity"]["requiredDuringSchedulingIgnoredDuringExecution"]
    assert aff["nodeSelectorTerms"][0]["matchFields"][0]["values"] == ["normal"]

    tolerant = fixtures.make_fake_daemon_set(
        "agent2", "100m", "128Mi", fixtures.with_tolerations([{"operator": "Exists"}])
    )
    pods = expand.pods_from_daemon_set(tolerant, [tainted, normal])
    assert len(pods) == 2


def test_cronjob_expansion():
    cj = fixtures.make_fake_cron_job("tick", completions=2)
    pods = expand.pods_from_cron_job(cj)
    assert len(pods) == 2
    assert pods[0].metadata.annotations[ANNO_WORKLOAD_KIND] == "Job"


def test_make_valid_pod_sanitization():
    pod = Pod.from_dict(
        {
            "kind": "Pod",
            "metadata": {"name": "p"},
            "spec": {
                "containers": [
                    {
                        "name": "c",
                        "env": [{"name": "A", "value": "B"}],
                        "volumeMounts": [{"name": "v", "mountPath": "/x"}],
                        "livenessProbe": {"exec": {"command": ["true"]}},
                    }
                ],
                "volumes": [{"name": "v", "persistentVolumeClaim": {"claimName": "c"}}],
            },
        }
    )
    valid = expand.make_valid_pod(pod)
    assert valid.metadata.namespace == "default"
    c = valid.raw["spec"]["containers"][0]
    assert "env" not in c and "volumeMounts" not in c and "livenessProbe" not in c
    assert valid.raw["spec"]["volumes"][0]["hostPath"]["path"] == "/tmp"
    assert "persistentVolumeClaim" not in valid.raw["spec"]["volumes"][0]


def test_selector_matching():
    node = fixtures.make_fake_node("n1", "4", "8Gi", "110", fixtures.with_labels({"disk": "ssd", "zone": "a"}))
    assert selectors.match_label_selector({"matchLabels": {"disk": "ssd"}}, node.metadata.labels)
    assert not selectors.match_label_selector(None, node.metadata.labels)
    assert selectors.match_label_selector({}, node.metadata.labels)  # empty matches all
    assert selectors.match_label_selector(
        {"matchExpressions": [{"key": "disk", "operator": "In", "values": ["ssd", "hdd"]}]},
        node.metadata.labels,
    )
    assert selectors.match_label_selector(
        {"matchExpressions": [{"key": "gpu", "operator": "DoesNotExist"}]}, node.metadata.labels
    )
    term = {"matchExpressions": [{"key": "zone", "operator": "NotIn", "values": ["b"]}]}
    assert selectors.match_node_selector_term(term, node)
    assert not selectors.match_node_selector_term({}, node)  # empty term matches nothing


def test_taint_toleration():
    from opensim_tpu.models import Taint, Toleration

    taint = Taint(key="k", value="v", effect="NoSchedule")
    assert selectors.toleration_tolerates_taint(Toleration(key="k", operator="Exists"), taint)
    assert selectors.toleration_tolerates_taint(Toleration(key="k", operator="Equal", value="v"), taint)
    assert not selectors.toleration_tolerates_taint(Toleration(key="k", operator="Equal", value="w"), taint)
    assert selectors.toleration_tolerates_taint(Toleration(operator="Exists"), taint)
    assert not selectors.toleration_tolerates_taint(
        Toleration(key="k", operator="Exists", effect="NoExecute"), taint
    )
    assert selectors.find_untolerated_taint([taint], []) is taint
    assert selectors.find_untolerated_taint([taint], [Toleration(operator="Exists")]) is None


def test_load_repo_examples():
    rt = expand.load_cluster_from_dir("example/cluster/demo")
    assert len(rt.nodes) == 4
    assert any("simon/node-local-storage" in n.metadata.annotations for n in rt.nodes)
    app, skipped = expand.resources_from_dicts(expand.load_yaml_objects("example/application/simple"))
    pods = expand.generate_pods_from_resources(app, rt.nodes)
    # 1 bare pod + 3 deployment + 2 replicaset + 2 job + 6 sts + 2 daemonset
    # (the exporter DS tolerates no control-plane taint → workers only)
    assert len(pods) == 16


@pytest.mark.skipif(
    not os.path.isdir("/root/reference/example"), reason="reference checkout not mounted"
)
def test_load_reference_examples():
    rt = expand.load_cluster_from_dir("/root/reference/example/cluster/demo_1")
    assert len(rt.nodes) == 4
    assert any("simon/node-local-storage" in n.metadata.annotations for n in rt.nodes)
    app, skipped = expand.resources_from_dicts(
        expand.load_yaml_objects("/root/reference/example/application/simple")
    )
    pods = expand.generate_pods_from_resources(app, rt.nodes)
    # 1 bare pod + 4 deployment + 2 replicaset + 2 job + 5 sts + 3 daemonset (all nodes tolerated)
    assert len(pods) == 17


def test_touch_bumps_global_epoch_thread_safely():
    import threading

    from opensim_tpu.models.objects import Pod, touch_epoch

    pods = [Pod() for _ in range(8)]
    before = touch_epoch()

    def hammer(p):
        for _ in range(500):
            p.touch()

    threads = [threading.Thread(target=hammer, args=(p,)) for p in pods]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert touch_epoch() - before == 8 * 500  # no lost increments
    assert all(p.local_version == 500 for p in pods)
