# lint-corpus-path: opensim_tpu/engine/fixture.py
from opensim_tpu.engine import reasons


def decode(UnscheduledPod, pod, node):
    return [UnscheduledPod(pod, reasons.node_not_found(node))]
