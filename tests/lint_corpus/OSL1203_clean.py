# lint-corpus-path: opensim_tpu/server/fixture.py
import threading
import time

_lock = threading.Lock()


def fine():
    time.sleep(0.1)  # outside the critical section
    with _lock:
        pass
