# lint-corpus-path: opensim_tpu/encoding/fixture.py
import numpy as np

from opensim_tpu.encoding.dtypes import FLOAT_DTYPE


def build(n):
    return np.zeros((n,), dtype=FLOAT_DTYPE)
