# lint-corpus-path: opensim_tpu/engine/fixture.py
from opensim_tpu.resilience.deadline import check_deadline


def prepare_things(cluster, encode):
    check_deadline("prepare")  # phase boundary with no span
    return encode(cluster)
