# lint-corpus-path: opensim_tpu/encoding/fixture_osl1803.py
"""Fire: rank mismatch against the declared axes. ``EncodedCluster.alloc``
is contracted ``(N, R)`` — rank 2 — but the binding supplies a rank-1
array."""

import numpy as np

from opensim_tpu.encoding.dtypes import FLOAT_DTYPE
from opensim_tpu.encoding.state import EncodedCluster


def build(n):
    alloc = np.zeros((n,), dtype=FLOAT_DTYPE)  # rank 1, contract wants (N, R)
    return EncodedCluster(alloc=alloc)
