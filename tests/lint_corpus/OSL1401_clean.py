# lint-corpus-path: opensim_tpu/engine/fixture.py
import os

from opensim_tpu.utils import envknobs

FLAG = envknobs.raw("OPENSIM_EAGER_PREPARE", "0")  # the registry read path
os.environ["OPENSIM_FIXTURE_FLAG"] = "1"  # writes stay legal
