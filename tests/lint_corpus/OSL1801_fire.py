# lint-corpus-path: opensim_tpu/encoding/fixture_osl1801.py
"""Fire: an array built without a policy dtype reaches a contracted
arena field. ``np.zeros`` defaults to float64; ``EncodedCluster.alloc``
is contracted FLOAT_DTYPE (float32). The finding anchors at the
creation site, not the constructor."""

import numpy as np

from opensim_tpu.encoding.state import EncodedCluster


def build(n, r):
    alloc = np.zeros((n, r))  # no dtype= -> numpy f64, off policy
    return EncodedCluster(alloc=alloc)
