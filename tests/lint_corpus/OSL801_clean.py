# lint-corpus-path: opensim_tpu/server/fixture.py
def follow(client, rv, handle, stop):
    while not stop.is_set():  # supervised condition
        for ev in client.watch("pods", rv):
            handle(ev)
