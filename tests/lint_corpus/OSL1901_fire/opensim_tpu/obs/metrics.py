"""Fixture: FAMILIES and the doc table drifted BOTH directions —
`simon_registered_only_total` has no doc row, and the doc documents
`simon_doc_only_total` which is not registered."""

FAMILIES = {
    "simon_requests_total": ("Requests served by endpoint", "counter"),
    "simon_registered_only_total": ("Registered but undocumented", "counter"),
}
