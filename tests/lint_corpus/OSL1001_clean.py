# lint-corpus-path: opensim_tpu/server/admission.py
class Controller:
    def consume(self):
        with self._cond:
            while not self._queue and not self._closed:
                self._cond.wait()  # the one legal wait: on the held cond
            item = self._queue.popleft()
            self._cond.notify_all()
        return item
