"""Fixture: registry and doc table name exactly the same families."""

FAMILIES = {
    "simon_requests_total": ("Requests served by endpoint", "counter"),
    "simon_request_seconds": ("Whole-request latency", "histogram"),
}
