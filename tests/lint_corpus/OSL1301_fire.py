# lint-corpus-path: opensim_tpu/server/fixture.py
def grab():
    return open("state/journal-00000001.seg", "ab")  # foreign journal write
