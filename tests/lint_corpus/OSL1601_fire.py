# lint-corpus-path: opensim_tpu/server/fixture.py
import time

import jax


def helper(c):
    return c * time.time()  # clock read two call levels under the trace


def body(carry, x):
    return helper(carry), x


def outer(xs):
    return jax.lax.scan(body, 0, xs)
