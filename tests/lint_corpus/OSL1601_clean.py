# lint-corpus-path: opensim_tpu/server/fixture.py
import time

import jax


def helper(c):
    return c * 2


def body(carry, x):
    return helper(carry), x


def outer(xs):
    return jax.lax.scan(body, 0, xs)


def host_driver(xs):
    t0 = time.time()  # not reachable from the traced region
    return outer(xs), time.time() - t0
