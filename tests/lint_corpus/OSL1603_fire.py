# lint-corpus-path: opensim_tpu/server/fixture.py
from urllib.parse import parse_qs


def handler(query):
    name = parse_qs(query).get("f", [""])[-1]
    with open(name) as fh:  # http-query taint straight into open()
        return fh.read()
