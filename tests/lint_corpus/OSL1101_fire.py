# lint-corpus-path: opensim_tpu/obs/capacity_fixture.py
from opensim_tpu.obs.metrics import CounterVec

REQS = CounterVec("simon_fixture_total", "ad-hoc family off the registry")
