# lint-corpus-path: opensim_tpu/server/fixture.py
import threading

from opensim_tpu.resilience.deadline import current_deadline, deadline_scope


def worker(dl):
    with deadline_scope(dl):  # explicit handoff
        return current_deadline()


def spawn(dl):
    threading.Thread(target=worker, args=(dl,)).start()
