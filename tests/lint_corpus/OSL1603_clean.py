# lint-corpus-path: opensim_tpu/server/fixture.py
from urllib.parse import parse_qs

from opensim_tpu.utils.validate import sanitizer


@sanitizer
def report_name(raw):
    if not raw.isidentifier():
        raise ValueError(f"invalid report name {raw!r}")
    return raw


def handler(query):
    name = report_name(parse_qs(query).get("f", [""])[-1])
    with open(name) as fh:  # validated first: clean
        return fh.read()
