# lint-corpus-path: opensim_tpu/server/fixture.py
def grab(control_name):
    from opensim_tpu.server.fleet import FleetReader  # the sanctioned path

    return FleetReader(control_name).attach()
