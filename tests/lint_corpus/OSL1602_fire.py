# lint-corpus-path: opensim_tpu/server/fixture.py
import jax
import jax.numpy as jnp


class Recorder:
    @jax.jit
    def step(self, x):
        y = jnp.sum(x)
        self.last = y  # tracer stored into state that outlives the trace
        return y
