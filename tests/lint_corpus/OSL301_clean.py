# lint-corpus-path: opensim_tpu/engine/fixture.py
import hashlib


def fingerprint(d):
    h = hashlib.blake2b()
    for k in sorted(d.items()):
        h.update(str(k).encode())
    return h.hexdigest()
