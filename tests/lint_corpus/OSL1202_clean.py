# lint-corpus-path: opensim_tpu/server/fixture.py
import threading

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()


def one():
    with LOCK_A:
        with LOCK_B:
            pass


def two():
    with LOCK_A:
        with LOCK_B:  # same order everywhere: no cycle
            pass
