# lint-corpus-path: opensim_tpu/server/fixture.py
import threading

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()


def ab():
    with LOCK_A:
        with LOCK_B:
            pass


def ba():
    with LOCK_B:
        with LOCK_A:  # A->B and B->A: inversion cycle
            pass
