# lint-corpus-path: opensim_tpu/server/fixture.py
def follow(client, rv, handle):
    while True:
        try:
            for ev in client.watch("pods", rv):
                handle(ev)
        except OSError:
            continue  # reconnect forever, no supervision
