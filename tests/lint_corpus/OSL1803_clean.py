# lint-corpus-path: opensim_tpu/encoding/fixture_osl1803.py
"""Clean: the binding's symbolic shape ``(n, r)`` normalizes to the
contracted axes ``(N, R)`` (axis matching is case-insensitive over the
vocabulary the contracts declare)."""

import numpy as np

from opensim_tpu.encoding.dtypes import FLOAT_DTYPE
from opensim_tpu.encoding.state import EncodedCluster


def build(n, r):
    alloc = np.zeros((n, r), dtype=FLOAT_DTYPE)
    return EncodedCluster(alloc=alloc)
