# lint-corpus-path: opensim_tpu/obs/metrics.py
CounterVec = object  # the registry module itself constructs the primitives


def make_counter(name, help_):
    return CounterVec
