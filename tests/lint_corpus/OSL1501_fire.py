# lint-corpus-path: opensim_tpu/server/fixture.py
def dispatch(step, drain, other):
    if step == "drain-wave":  # ad-hoc step dispatch outside the registry
        return drain()
    return other()
