# lint-corpus-path: opensim_tpu/server/fixture.py
def inspect():
    return open("state/journal-00000001.seg", "rb")  # read-only: legal
