# lint-corpus-path: opensim_tpu/server/fixture.py
from multiprocessing import shared_memory


def grab(name):
    return shared_memory.SharedMemory(name=name)  # foreign attach
