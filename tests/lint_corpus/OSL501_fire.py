# lint-corpus-path: opensim_tpu/engine/fixture.py
def swallow(risky):
    try:
        risky()
    except Exception:
        pass
