# lint-corpus-path: opensim_tpu/server/fixture.py
import jax
import jax.numpy as jnp


class Recorder:
    @jax.jit
    def step(self, x):
        y = jnp.sum(x)
        local = [y]  # stays inside the trace frame
        return local[0]
