# lint-corpus-path: opensim_tpu/server/fixture.py
import urllib.request


def fetch(url):
    while True:
        try:
            return urllib.request.urlopen(url)
        except OSError:
            pass  # swallow and hammer forever
