# lint-corpus-path: opensim_tpu/engine/fixture.py
import hashlib


def fingerprint(d):
    h = hashlib.blake2b()
    for k, v in d.items():  # dict order feeds the hash
        h.update(str((k, v)).encode())
    return h.hexdigest()
