# lint-corpus-path: opensim_tpu/engine/fixture.py
def translated(risky):
    try:
        risky()
    except Exception as e:
        raise RuntimeError(str(e)) from e
