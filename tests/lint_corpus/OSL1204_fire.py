# lint-corpus-path: opensim_tpu/server/fixture.py
import threading

from opensim_tpu.resilience.deadline import check_deadline


class Worker(threading.Thread):
    def run(self):
        check_deadline("phase")  # ambient contextvar read in a new thread
