# lint-corpus-path: opensim_tpu/encoding/fixture_osl1801.py
"""Clean: the same binding with the policy dtype named at creation."""

import numpy as np

from opensim_tpu.encoding.dtypes import FLOAT_DTYPE
from opensim_tpu.encoding.state import EncodedCluster


def build(n, r):
    alloc = np.zeros((n, r), dtype=FLOAT_DTYPE)
    return EncodedCluster(alloc=alloc)
