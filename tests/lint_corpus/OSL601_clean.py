# lint-corpus-path: opensim_tpu/server/fixture.py
import time
import urllib.request


def fetch(url, attempts=3):
    for k in range(attempts):
        try:
            return urllib.request.urlopen(url)
        except OSError:
            if k == attempts - 1:
                raise
            time.sleep(0.1 * 2 ** k)  # bounded + exponential backoff
