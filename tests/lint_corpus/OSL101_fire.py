# lint-corpus-path: opensim_tpu/engine/fixture.py
import time

import jax


@jax.jit
def step(x):
    t = time.monotonic()  # host clock baked in at trace time
    return x + t
