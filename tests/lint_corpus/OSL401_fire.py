# lint-corpus-path: opensim_tpu/engine/fixture.py
from opensim_tpu.engine.prepcache import fingerprint_cluster


def bad(cluster, extra_pod):
    fp = fingerprint_cluster(cluster)
    cluster.pods.append(extra_pod)  # mutation after the content was keyed
    return fp
