# lint-corpus-path: opensim_tpu/planner/campaign.py
def dispatch(step, drain, other):
    if step == "drain-wave":  # the registry module owns step dispatch
        return drain()
    return other()
