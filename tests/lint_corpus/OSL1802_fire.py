# lint-corpus-path: opensim_tpu/encoding/fixture_osl1802.py
"""Fire: a silent f32 x i64 -> f64 promotion inside a helper reaches
``EncodedCluster.alloc`` (contract FLOAT_DTYPE = f32) through the
helper's return value — the interprocedural case. The finding anchors
at the multiplication, not at the constructor."""

import numpy as np

from opensim_tpu.encoding.dtypes import FLOAT_DTYPE
from opensim_tpu.encoding.state import EncodedCluster


def mix(n, r):
    a = np.zeros((n, r), dtype=FLOAT_DTYPE)
    idx = np.arange(n)  # numpy default: i64
    return a * idx.reshape((n, 1))  # f32 x i64 -> f64, silently


def build(n, r):
    return EncodedCluster(alloc=mix(n, r))
