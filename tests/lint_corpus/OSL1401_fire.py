# lint-corpus-path: opensim_tpu/engine/fixture.py
import os

FLAG = os.environ.get("OPENSIM_FIXTURE_FLAG", "0")  # unregistered knob read
