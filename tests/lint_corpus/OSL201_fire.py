# lint-corpus-path: opensim_tpu/encoding/fixture.py
import numpy as np


def build(n):
    return np.zeros((n,))  # default dtype drifts off the Go parity policy
