// faithful copy: layouts byte-agree
// abi-begin: ScanArgs
struct ScanArgs {
  int64_t N, R;
  double w_x;
  const uint8_t* node_valid;
};
// abi-end: ScanArgs
int64_t opensim_abi_version() { return 4; }
