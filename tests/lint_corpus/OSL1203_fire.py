# lint-corpus-path: opensim_tpu/server/fixture.py
import threading
import time

_lock = threading.Lock()


def bad_sleep():
    with _lock:
        time.sleep(0.1)  # blocks every waiter of _lock
