"""Corpus mini native packing — node_domain drifted wide (i64) on both
the ctypes mirror and the C++ struct next door, consistently, while the
contract registry still says INT_DTYPE (i32)."""

import ctypes

_F32 = ctypes.POINTER(ctypes.c_float)
_I64 = ctypes.POINTER(ctypes.c_int64)

_BUFFERS = [
    ("alloc", _F32, "f32"),
    ("node_domain", _I64, "i64"),
    ("used", _F32, "f32"),
]
