// Corpus mini engine source — agrees with the drifted mirror (i64), so
// OSL1604's cc-vs-mirror comparison stays green; only the contract
// registry knows the field should be i32.
struct ScanArgs {
  int64_t N, R, Tk;
  const float* alloc;          // [N,R]
  const int64_t* node_domain;  // [N,Tk]
  float* used;                 // [N,R]
};
