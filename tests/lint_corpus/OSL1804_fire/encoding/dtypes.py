"""Corpus mini contract registry (OSL1804 fire fixture).

``node_domain`` is contracted INT_DTYPE (i32), but BOTH native sides in
this fixture tree marshal it as i64 — the drift axis OSL1604 cannot see
(the ctypes mirror and the C++ struct agree with each other)."""

import numpy as np

FLOAT_DTYPE = np.float32
INT_DTYPE = np.int32

AXIS_ALIASES = {
    "n_topo": "Tk",
}

ARENA_CONTRACTS = {
    "alloc": ("FLOAT_DTYPE", ("N", "R")),
    "node_domain": ("INT_DTYPE", ("N", "Tk")),
}

STATE_CONTRACTS = {
    "used": ("FLOAT_DTYPE", ("N", "R")),
}

BUFFER_FIELD_ALIASES = {}

KERNEL_ARG_CONTRACTS = {}

STRUCT_PARAM_NAMES = {}
