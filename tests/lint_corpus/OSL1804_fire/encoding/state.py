"""Corpus mini arena structs — field sets mirror the registry keys."""

from typing import NamedTuple

import numpy as np


class EncodedCluster(NamedTuple):
    alloc: np.ndarray
    node_domain: np.ndarray


class ScanState(NamedTuple):
    used: np.ndarray
