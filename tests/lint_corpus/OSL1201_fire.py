# lint-corpus-path: opensim_tpu/server/fixture.py
import threading


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []  # guarded-by: _lock

    def good(self, x):
        with self._lock:
            self._items.append(x)

    def bad(self, x):
        self._items.append(x)  # touched outside the critical section
