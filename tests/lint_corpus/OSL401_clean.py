# lint-corpus-path: opensim_tpu/engine/fixture.py
from opensim_tpu.engine.prepcache import fingerprint_cluster


def fixed(cluster, cache, extra_pod):
    fp = fingerprint_cluster(cluster)
    cluster.pods.append(extra_pod)
    cache.invalidate(cluster)  # the sanctioned escape
    return fp
