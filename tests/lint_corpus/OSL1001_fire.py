# lint-corpus-path: opensim_tpu/server/admission.py
import time


class Controller:
    def submit(self, t):
        with self._cond:
            time.sleep(0.1)  # blocking I/O while holding the dispatch lock
            self._queue.append(t)
            self._cond.notify()
