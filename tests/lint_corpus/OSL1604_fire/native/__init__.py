import ctypes

_DIMS = ["N", "R"]
_WEIGHTS = ["w_x"]
_U8 = ctypes.POINTER(ctypes.c_uint8)
_BUFFERS = [("node_valid", _U8, "u8")]
ABI_VERSION = 4


class ScanArgs(ctypes.Structure):
    _fields_ = (
        [(n, ctypes.c_int64) for n in _DIMS]
        + [(n, ctypes.c_double) for n in _WEIGHTS]
        + [(n, t) for n, t, _ in _BUFFERS]
    )
