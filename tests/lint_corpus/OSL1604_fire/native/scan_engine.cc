// mutated copy: the dims order drifted (R before N) vs the ctypes mirror
// abi-begin: ScanArgs
struct ScanArgs {
  int64_t R, N;
  double w_x;
  const uint8_t* node_valid;
};
// abi-end: ScanArgs
int64_t opensim_abi_version() { return 4; }
