# lint-corpus-path: opensim_tpu/encoding/fixture_osl1802.py
"""Clean: the index factor is created at the policy float width, so the
product stays f32 end to end."""

import numpy as np

from opensim_tpu.encoding.dtypes import FLOAT_DTYPE
from opensim_tpu.encoding.state import EncodedCluster


def mix(n, r):
    a = np.zeros((n, r), dtype=FLOAT_DTYPE)
    idx = np.arange(n, dtype=FLOAT_DTYPE)
    return a * idx.reshape((n, 1))


def build(n, r):
    return EncodedCluster(alloc=mix(n, r))
