# lint-corpus-path: opensim_tpu/engine/fixture.py
import time

import jax


@jax.jit
def step(x):
    return x + 1


def host_driver(xs):
    t0 = time.monotonic()  # fine: not traced
    return step(xs), time.monotonic() - t0
