"""Corpus mini native packing — widths match the contract registry."""

import ctypes

_F32 = ctypes.POINTER(ctypes.c_float)
_I32 = ctypes.POINTER(ctypes.c_int32)

_BUFFERS = [
    ("alloc", _F32, "f32"),
    ("node_domain", _I32, "i32"),
    ("used", _F32, "f32"),
]
