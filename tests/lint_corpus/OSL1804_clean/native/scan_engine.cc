// Corpus mini engine source — widths match the contract registry.
struct ScanArgs {
  int64_t N, R, Tk;
  const float* alloc;          // [N,R]
  const int32_t* node_domain;  // [N,Tk]
  float* used;                 // [N,R]
};
