"""Corpus mini contract registry (OSL1804 clean fixture): the registry,
the policy constants, the struct field sets and both native sides all
agree on every width."""

import numpy as np

FLOAT_DTYPE = np.float32
INT_DTYPE = np.int32

AXIS_ALIASES = {
    "n_topo": "Tk",
}

ARENA_CONTRACTS = {
    "alloc": ("FLOAT_DTYPE", ("N", "R")),
    "node_domain": ("INT_DTYPE", ("N", "Tk")),
}

STATE_CONTRACTS = {
    "used": ("FLOAT_DTYPE", ("N", "R")),
}

BUFFER_FIELD_ALIASES = {}

KERNEL_ARG_CONTRACTS = {}

STRUCT_PARAM_NAMES = {}
