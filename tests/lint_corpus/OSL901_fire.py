# lint-corpus-path: opensim_tpu/engine/fixture.py
def decode(UnscheduledPod, pod):
    return [UnscheduledPod(pod, "no nodes matched")]  # inline reason string
