"""OSL12xx whole-program concurrency rules + the lockwatch runtime
sanitizer: each rule fires on a known-bad fixture and stays silent on the
disciplined twin, attribution sees through one call level and across
modules, suppressions are honored, and a seeded A→B/B→A lock-order
inversion is caught in-process by the runtime half (`make tsan`)."""

import textwrap
import threading
import time

import pytest

from opensim_tpu.analysis import lint_paths, lint_source
from opensim_tpu.analysis import lockwatch
from opensim_tpu.analysis.lockwatch import LockWatch

# rule path scoping: OSL12xx excludes tests/ and tools/, OSL1203
# additionally excludes the OSL1001 modules (admission/pool/rest)
FIX = "opensim_tpu/server/fixture.py"


def _codes(src, path=FIX, rules=None):
    return [f.code for f in lint_source(textwrap.dedent(src), path=path, rules=rules)]


# ---------------------------------------------------------------------------
# OSL1201 unguarded-shared-state
# ---------------------------------------------------------------------------


def test_unguarded_shared_state_fires_outside_the_lock():
    src = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []  # guarded-by: _lock

        def good(self, x):
            with self._lock:
                self._items.append(x)

        def bad(self, x):
            self._items.append(x)

        def bad_read(self):
            return len(self._items)
    """
    codes = _codes(src, rules=["unguarded-shared-state"])
    assert codes == ["OSL1201", "OSL1201"]  # bad() mutate + bad_read() load


def test_unguarded_shared_state_init_publication_is_exempt():
    src = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []  # guarded-by: _lock
            self._items.append(0)   # happens-before any thread start
    """
    assert _codes(src, rules=["unguarded-shared-state"]) == []


def test_unguarded_shared_state_attributes_through_one_call_level():
    # _append itself takes no lock, but its EVERY call site is inside the
    # lock's critical section — the call-graph attribution keeps locked
    # helper pyramids annotation-clean
    src = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []  # guarded-by: _lock

        def add(self, x):
            with self._lock:
                self._append(x)

        def add2(self, x):
            with self._lock:
                self._append(x)

        def _append(self, x):
            self._items.append(x)
    """
    assert _codes(src, rules=["unguarded-shared-state"]) == []
    # one unlocked call site breaks the attribution for the helper
    leaky = src + """
    def sneak(b: "Box"):
        b._append(9)
    """
    codes = _codes(leaky, rules=["unguarded-shared-state"])
    assert codes == ["OSL1201"]


def test_unguarded_shared_state_unresolvable_guard_is_a_finding():
    src = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []  # guarded-by: _lokc
    """
    findings = lint_source(
        textwrap.dedent(src), path=FIX, rules=["unguarded-shared-state"]
    )
    assert [f.code for f in findings] == ["OSL1201"]
    assert "does not resolve" in findings[0].message


def test_unguarded_shared_state_suppression():
    src = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []  # guarded-by: _lock

        def bad(self, x):
            self._items.append(x)  # opensim-lint: disable=unguarded-shared-state
    """
    assert _codes(src, rules=["unguarded-shared-state"]) == []


def test_unguarded_shared_state_cross_module(tmp_path, monkeypatch):
    # the whole point of the ProjectContext: the lock lives in one module,
    # the undisciplined access in another
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "a.py").write_text(
        textwrap.dedent(
            """
            import threading

            class Store:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.items = []  # guarded-by: _lock

            STORE = Store()
            """
        )
    )
    (pkg / "b.py").write_text(
        textwrap.dedent(
            """
            from pkg.a import STORE

            def poke():
                STORE.items.append(1)

            def polite():
                with STORE._lock:
                    STORE.items.append(2)
            """
        )
    )
    # function-level `from pkg import a` binds the submodule; resolution
    # must see through the deferred-import idiom too
    (pkg / "c.py").write_text(
        textwrap.dedent(
            """
            def poke2():
                from pkg import a
                a.STORE.items.append(3)
            """
        )
    )
    monkeypatch.chdir(tmp_path)  # relative paths: no test_* fragment
    findings = lint_paths(["pkg"], rules=["unguarded-shared-state"])
    assert sorted((f.path, f.code) for f in findings) == [
        ("pkg/b.py", "OSL1201"),
        ("pkg/c.py", "OSL1201"),
    ]


def test_unguarded_shared_state_malformed_guard_token_is_a_finding():
    # a one-keystroke typo (trailing dot) must yield the unresolved-guard
    # finding, not a SyntaxError out of the analyzer
    src = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []  # guarded-by: _lock.
    """
    findings = lint_source(
        textwrap.dedent(src), path=FIX, rules=["unguarded-shared-state"]
    )
    assert [f.code for f in findings] == ["OSL1201"]
    assert "does not resolve" in findings[0].message


def test_unguarded_shared_state_guard_tokens_resolve_through_imports(tmp_path, monkeypatch):
    # a bare token naming an imported module-global lock, and a dotted
    # token resolved through `from . import locks` in a package __init__
    # (whose module name already IS the package — one less level to strip)
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "locks.py").write_text("import threading\nGLOBAL_LOCK = threading.Lock()\n")
    (pkg / "__init__.py").write_text(
        textwrap.dedent(
            """
            from . import locks

            class Reg:
                def __init__(self):
                    self.n = 0  # guarded-by: locks.GLOBAL_LOCK

                def good(self):
                    with locks.GLOBAL_LOCK:
                        self.n += 1

                def bad(self):
                    self.n += 1
            """
        )
    )
    (pkg / "user.py").write_text(
        textwrap.dedent(
            """
            from pkg.locks import GLOBAL_LOCK

            class Counter:
                def __init__(self):
                    self.n = 0  # guarded-by: GLOBAL_LOCK

                def good(self):
                    with GLOBAL_LOCK:
                        self.n += 1

                def bad(self):
                    self.n += 1
            """
        )
    )
    monkeypatch.chdir(tmp_path)
    findings = lint_paths(["pkg"], rules=["unguarded-shared-state"])
    # both guards resolve (no "does not resolve" noise), both bad() writes fire
    assert all("does not resolve" not in f.message for f in findings)
    assert sorted((f.path, f.code) for f in findings) == [
        ("pkg/__init__.py", "OSL1201"),
        ("pkg/user.py", "OSL1201"),
    ]


# ---------------------------------------------------------------------------
# OSL1202 lock-order-inversion
# ---------------------------------------------------------------------------


def test_lock_order_inversion_fires_on_directly_nested_cycle():
    src = """
    import threading

    LOCK_A = threading.Lock()
    LOCK_B = threading.Lock()

    def ab():
        with LOCK_A:
            with LOCK_B:
                pass

    def ba():
        with LOCK_B:
            with LOCK_A:
                pass
    """
    findings = lint_source(
        textwrap.dedent(src), path=FIX, rules=["lock-order-inversion"]
    )
    assert [f.code for f in findings] == ["OSL1202"]
    assert "cycle" in findings[0].message


def test_lock_order_inversion_silent_on_consistent_order():
    src = """
    import threading

    LOCK_A = threading.Lock()
    LOCK_B = threading.Lock()

    def one():
        with LOCK_A:
            with LOCK_B:
                pass

    def two():
        with LOCK_A:
            with LOCK_B:
                pass
    """
    assert _codes(src, rules=["lock-order-inversion"]) == []


def test_lock_order_inversion_attributed_through_a_call():
    src = """
    import threading

    LOCK_A = threading.Lock()
    LOCK_B = threading.Lock()

    def ab():
        with LOCK_A:
            with LOCK_B:
                pass

    def helper():
        with LOCK_A:
            pass

    def inverted():
        with LOCK_B:
            helper()
    """
    codes = _codes(src, rules=["lock-order-inversion"])
    assert codes == ["OSL1202"]


# ---------------------------------------------------------------------------
# OSL1203 blocking-call-under-lock
# ---------------------------------------------------------------------------


def test_blocking_call_under_lock_fires_on_sleep_and_subprocess():
    src = """
    import subprocess
    import threading
    import time

    _lock = threading.Lock()

    def bad_sleep():
        with _lock:
            time.sleep(0.1)

    def bad_subprocess():
        with _lock:
            subprocess.run(["true"])

    def fine():
        time.sleep(0.1)
        with _lock:
            pass
    """
    codes = _codes(src, rules=["blocking-call-under-lock"])
    assert codes == ["OSL1203", "OSL1203"]


def test_blocking_call_under_lock_sees_one_call_level_deep():
    src = """
    import threading
    import time

    _lock = threading.Lock()

    def helper():
        time.sleep(0.1)

    def bad():
        with _lock:
            helper()
    """
    findings = lint_source(
        textwrap.dedent(src), path=FIX, rules=["blocking-call-under-lock"]
    )
    assert [f.code for f in findings] == ["OSL1203"]
    assert "helper" in findings[0].message


def test_blocking_call_under_lock_exempts_wait_on_held_condition():
    src = """
    import threading

    class Q:
        def __init__(self):
            self._cond = threading.Condition()
            self._items = []  # guarded-by: _cond

        def get(self):
            with self._cond:
                while not self._items:
                    self._cond.wait()   # releases the held lock: legal
                return self._items.pop()
    """
    assert _codes(src, rules=["blocking-call-under-lock"]) == []


def test_blocking_call_under_lock_suppression():
    src = """
    import threading
    import time

    _lock = threading.Lock()

    def justified():
        with _lock:
            time.sleep(0.01)  # opensim-lint: disable=blocking-call-under-lock
    """
    assert _codes(src, rules=["blocking-call-under-lock"]) == []


# ---------------------------------------------------------------------------
# OSL1204 thread-unsafe-contextvar
# ---------------------------------------------------------------------------


def test_thread_unsafe_contextvar_fires_on_ambient_read_in_thread_target():
    src = """
    import threading

    from opensim_tpu.resilience.deadline import current_deadline

    def worker():
        d = current_deadline()   # contextvars do not cross threads: None
        return d

    def spawn():
        threading.Thread(target=worker).start()
    """
    codes = _codes(src, rules=["thread-unsafe-contextvar"])
    assert codes == ["OSL1204"]


def test_thread_unsafe_contextvar_silent_with_explicit_handoff():
    src = """
    import threading

    from opensim_tpu.resilience.deadline import current_deadline, deadline_scope

    def worker(dl):
        with deadline_scope(dl):
            return current_deadline()

    def spawn(dl):
        threading.Thread(target=worker, args=(dl,)).start()
    """
    assert _codes(src, rules=["thread-unsafe-contextvar"]) == []


def test_thread_unsafe_contextvar_fires_on_thread_subclass_run():
    src = """
    import threading

    from opensim_tpu.resilience.deadline import check_deadline

    class Worker(threading.Thread):
        def run(self):
            check_deadline("phase")
    """
    codes = _codes(src, rules=["thread-unsafe-contextvar"])
    assert codes == ["OSL1204"]


# ---------------------------------------------------------------------------
# lockwatch — the runtime half
# ---------------------------------------------------------------------------


def test_lockwatch_self_test_catches_seeded_inversion():
    assert lockwatch.self_test()


def test_lockwatch_catches_inversion_across_real_threads():
    # the seeded A→B/B→A pair, from two distinct threads: the order graph
    # is process-global, so no interleaving (or deadlock) is needed
    w = LockWatch(hold_ms=10_000)
    a = w.wrap(threading.Lock(), "fixture.py:1")
    b = w.wrap(threading.Lock(), "fixture.py:2")

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    for fn in (ab, ba):
        t = threading.Thread(target=fn)
        t.start()
        t.join()
    rep = w.report()
    assert len(rep["inversions"]) == 1
    inv = rep["inversions"][0]
    assert {inv["acquiring"], inv["held"]} == {"fixture.py:1", "fixture.py:2"}
    assert "fixture.py:1" in inv["cycle"] and "fixture.py:2" in inv["cycle"]


def test_lockwatch_same_creation_site_is_unordered():
    # two cache entries' locks share one lock class: taking them in both
    # orders is NOT an inversion (lockdep-style keying by creation site)
    w = LockWatch(hold_ms=10_000)
    a = w.wrap(threading.Lock(), "entry.py:7")
    b = w.wrap(threading.Lock(), "entry.py:7")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert w.report()["inversions"] == []


def test_lockwatch_hold_outlier_and_exemptions():
    w = LockWatch(hold_ms=5.0, hold_exempt_sites=())
    hot = w.wrap(threading.Lock(), "hot.py:1")
    with hot:
        time.sleep(0.02)
    rep = w.report()
    assert len(rep["hold_outliers"]) == 1
    assert rep["hold_outliers"][0]["lock"] == "hot.py:1"
    # site-substring exemption (OPENSIM_LOCKWATCH_HOLD_EXEMPT)
    w2 = LockWatch(hold_ms=5.0, hold_exempt_sites=("hot.py",))
    hot2 = w2.wrap(threading.Lock(), "hot.py:1")
    with hot2:
        time.sleep(0.02)
    assert w2.report()["hold_outliers"] == []
    # per-lock exemption (`# lockwatch: hold-exempt` creation-site marker)
    w3 = LockWatch(hold_ms=5.0, hold_exempt_sites=())
    hot3 = w3.wrap(threading.Lock(), "hot.py:1", hold_exempt=True)
    with hot3:
        time.sleep(0.02)
    assert w3.report()["hold_outliers"] == []


def test_lockwatch_cross_thread_release_clears_owner_stack():
    # a plain Lock may legally be released by a thread other than the
    # acquirer (handoff signaling); the owner's held-stack entry must be
    # closed, not leaked into false order edges on everything it takes next
    w = LockWatch(hold_ms=10_000)
    lk = w.wrap(threading.Lock(), "handoff.py:1")
    other = w.wrap(threading.Lock(), "other.py:1")
    acquired = threading.Event()
    released = threading.Event()

    def owner():
        lk.acquire()
        acquired.set()
        released.wait(2.0)  # main thread releases lk meanwhile
        with other:  # must NOT record handoff.py:1 -> other.py:1
            pass

    t = threading.Thread(target=owner)
    t.start()
    assert acquired.wait(2.0)
    lk.release()  # cross-thread release
    released.set()
    t.join()
    assert ("handoff.py:1", "other.py:1") not in w.edges
    assert w.report()["inversions"] == []
    # the owner's reentrancy count was cleared too: a later acquire of the
    # lock is first-level again (recorded, not mistaken for an RLock hold)
    base = w.report()["acquisitions"]
    with lk:
        pass
    assert w.report()["acquisitions"] == base + 1


def test_lockwatch_condition_wait_releases_the_lock():
    # a parked waiter must neither hold the lock (false inversions) nor be
    # charged hold time across the wait (false outliers)
    w = LockWatch(hold_ms=50.0, hold_exempt_sites=())
    tl = w.wrap(threading.Lock(), "cond.py:1")
    cond = threading.Condition(tl)
    ready = []

    def consumer():
        with cond:
            while not ready:
                cond.wait(timeout=2.0)

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.15)  # parked well past the hold threshold
    with cond:
        ready.append(1)
        cond.notify()
    t.join()
    rep = w.report()
    assert rep["inversions"] == []
    assert rep["hold_outliers"] == []


def test_lockwatch_install_instruments_repo_creations():
    if lockwatch.current() is not None:
        pytest.skip("a global lockwatch is already installed (tsan run)")
    w = lockwatch.install(hold_ms=10_000)
    try:
        plain = threading.Lock()
        exempt = threading.Lock()  # lockwatch: hold-exempt — fixture
        assert isinstance(plain, lockwatch.TracedLock)
        assert isinstance(exempt, lockwatch.TracedLock)
        assert not plain.hold_exempt
        assert exempt.hold_exempt
        assert "test_analysis_concurrency.py" in plain.name
        with plain:
            pass
        assert w.acquisitions >= 1
    finally:
        rep = lockwatch.uninstall()
    assert rep is not None and rep["locks"] >= 2
    assert not isinstance(threading.Lock(), lockwatch.TracedLock)
