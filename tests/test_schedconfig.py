"""--default-scheduler-config parsing and effect tests."""

from opensim_tpu.engine.schedconfig import DEFAULT_CONFIG, load_scheduler_config
from opensim_tpu.engine.simulator import AppResource, simulate
from opensim_tpu.models import ResourceTypes
from opensim_tpu.models import fixtures as fx


def test_load_scheduler_config(tmp_path):
    p = tmp_path / "sched.yaml"
    p.write_text(
        """apiVersion: kubescheduler.config.k8s.io/v1beta1
kind: KubeSchedulerConfiguration
profiles:
  - plugins:
      score:
        enabled:
          - name: NodeResourcesLeastAllocated
            weight: 5
        disabled:
          - name: PodTopologySpread
      filter:
        disabled:
          - name: TaintToleration
"""
    )
    cfg = load_scheduler_config(str(p))
    assert cfg.w_least == 5.0
    assert cfg.w_spread == 0.0
    assert not cfg.f_taints
    assert cfg.f_fit  # untouched defaults remain
    assert cfg.w_balanced == 1.0


def test_disabled_taint_filter_schedules_onto_tainted_node(tmp_path):
    cluster = ResourceTypes()
    cluster.nodes.append(
        fx.make_fake_node(
            "tainted", "8", "16Gi", "110",
            fx.with_taints([{"key": "dedicated", "value": "x", "effect": "NoSchedule"}]),
        )
    )
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("p", "100m", "128Mi"))

    # default config: blocked by the taint
    res = simulate(cluster, [AppResource("a", app)])
    assert len(res.unscheduled_pods) == 1

    cfg = DEFAULT_CONFIG._replace(f_taints=False)
    res = simulate(cluster, [AppResource("a", app)], sched_config=cfg)
    assert not res.unscheduled_pods


def test_default_config_file_is_identity(tmp_path):
    p = tmp_path / "empty.yaml"
    p.write_text("apiVersion: kubescheduler.config.k8s.io/v1beta1\nkind: KubeSchedulerConfiguration\n")
    assert load_scheduler_config(str(p)) == DEFAULT_CONFIG
