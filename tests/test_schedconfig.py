"""--default-scheduler-config parsing and effect tests."""

from opensim_tpu.engine.schedconfig import DEFAULT_CONFIG, load_scheduler_config
from opensim_tpu.engine.simulator import AppResource, simulate
from opensim_tpu.models import ResourceTypes
from opensim_tpu.models import fixtures as fx


def test_load_scheduler_config(tmp_path):
    p = tmp_path / "sched.yaml"
    p.write_text(
        """apiVersion: kubescheduler.config.k8s.io/v1beta1
kind: KubeSchedulerConfiguration
profiles:
  - plugins:
      score:
        enabled:
          - name: NodeResourcesLeastAllocated
            weight: 5
        disabled:
          - name: PodTopologySpread
      filter:
        disabled:
          - name: TaintToleration
"""
    )
    cfg = load_scheduler_config(str(p))
    assert cfg.w_least == 5.0
    assert cfg.w_spread == 0.0
    assert not cfg.f_taints
    assert cfg.f_fit  # untouched defaults remain
    assert cfg.w_balanced == 1.0


def test_disabled_taint_filter_schedules_onto_tainted_node(tmp_path):
    cluster = ResourceTypes()
    cluster.nodes.append(
        fx.make_fake_node(
            "tainted", "8", "16Gi", "110",
            fx.with_taints([{"key": "dedicated", "value": "x", "effect": "NoSchedule"}]),
        )
    )
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("p", "100m", "128Mi"))

    # default config: blocked by the taint
    res = simulate(cluster, [AppResource("a", app)])
    assert len(res.unscheduled_pods) == 1

    cfg = DEFAULT_CONFIG._replace(f_taints=False)
    res = simulate(cluster, [AppResource("a", app)], sched_config=cfg)
    assert not res.unscheduled_pods


def test_default_config_file_is_identity(tmp_path):
    p = tmp_path / "empty.yaml"
    p.write_text("apiVersion: kubescheduler.config.k8s.io/v1beta1\nkind: KubeSchedulerConfiguration\n")
    assert load_scheduler_config(str(p)) == DEFAULT_CONFIG


def test_extra_plugins_registry():
    """WithExtraRegistry parity: out-of-tree jittable filter and score
    plugins compose into the pipeline."""
    import jax.numpy as jnp

    cluster = ResourceTypes()
    for i in range(3):
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("w", 4, "100m", "128Mi"))

    def ban_node_zero(ec, st, u):
        return jnp.arange(ec.node_valid.shape[0]) != 0

    def prefer_node_two(ec, st, u, feasible):
        return jnp.where(jnp.arange(ec.node_valid.shape[0]) == 2, 100.0, 0.0)

    res = simulate(
        cluster,
        [AppResource("a", app)],
        extra_plugins=(("filter", ban_node_zero), ("score", prefer_node_two, 1000.0)),
    )
    assert not res.unscheduled_pods
    placed = {ns.node.metadata.name: len(ns.pods) for ns in res.node_status}
    assert placed.get("n0", 0) == 0  # custom filter banned it
    assert placed["n2"] == 4  # heavy custom score wins every bind


def test_extra_plugins_validation_and_reason():
    import pytest as _pytest

    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n0", "8", "16Gi"))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("p", "100m", "128Mi"))

    with _pytest.raises(ValueError):
        simulate(cluster, [AppResource("a", app)], extra_plugins=[("filter", lambda *a: None)])
    with _pytest.raises(ValueError):
        simulate(cluster, [AppResource("a", app)], extra_plugins=(("prefilter", lambda *a: None),))
    with _pytest.raises(ValueError):
        simulate(cluster, [AppResource("a", app)], extra_plugins=(("score", lambda *a: None),))

    import jax.numpy as jnp

    def ban_all(ec, st, u):
        return jnp.zeros(ec.node_valid.shape[0], bool)

    res = simulate(cluster, [AppResource("a", app)], extra_plugins=(("filter", ban_all),))
    assert len(res.unscheduled_pods) == 1
    assert "out-of-tree plugin" in res.unscheduled_pods[0].reason


def test_node_prefer_avoid_pods():
    """NodePreferAvoidPods (node_prefer_avoid_pods.go:47-82): an RS-owned
    pod avoids the annotated node when its controller uid matches."""
    import json as _json

    from opensim_tpu.models import expand as _expand

    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("avoided", "8", "16Gi"))
    cluster.nodes.append(fx.make_fake_node("ok", "8", "16Gi"))
    rs = fx.make_fake_replica_set("web", 2, "100m", "128Mi")
    pods = _expand.pods_from_replica_set(rs)
    rs_uid = pods[0].metadata.owner_references[0].uid
    cluster.nodes[0].metadata.annotations["scheduler.alpha.kubernetes.io/preferAvoidPods"] = _json.dumps(
        {"preferAvoidPods": [{"podSignature": {"podController": {"kind": "ReplicaSet", "uid": rs_uid}}}]}
    )
    app = ResourceTypes()
    app.pods.extend(pods)  # pre-expanded pods keep the known controller uid
    res = simulate(cluster, [AppResource("a", app)])
    assert not res.unscheduled_pods
    placed = {ns.node.metadata.name: len(ns.pods) for ns in res.node_status}
    # the 10000-weight avoidance dominates: both replicas land on 'ok'
    assert placed.get("avoided", 0) == 0
    assert placed["ok"] == 2
