"""--default-scheduler-config parsing and effect tests."""

from opensim_tpu.engine.schedconfig import DEFAULT_CONFIG, load_scheduler_config
from opensim_tpu.engine.simulator import AppResource, simulate
from opensim_tpu.models import ResourceTypes
from opensim_tpu.models import fixtures as fx


def test_load_scheduler_config(tmp_path):
    p = tmp_path / "sched.yaml"
    p.write_text(
        """apiVersion: kubescheduler.config.k8s.io/v1beta1
kind: KubeSchedulerConfiguration
profiles:
  - plugins:
      score:
        enabled:
          - name: NodeResourcesLeastAllocated
            weight: 5
        disabled:
          - name: PodTopologySpread
      filter:
        disabled:
          - name: TaintToleration
"""
    )
    cfg = load_scheduler_config(str(p))
    assert cfg.w_least == 5.0
    assert cfg.w_spread == 0.0
    assert not cfg.f_taints
    assert cfg.f_fit  # untouched defaults remain
    assert cfg.w_balanced == 1.0


def test_disabled_taint_filter_schedules_onto_tainted_node(tmp_path):
    cluster = ResourceTypes()
    cluster.nodes.append(
        fx.make_fake_node(
            "tainted", "8", "16Gi", "110",
            fx.with_taints([{"key": "dedicated", "value": "x", "effect": "NoSchedule"}]),
        )
    )
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("p", "100m", "128Mi"))

    # default config: blocked by the taint
    res = simulate(cluster, [AppResource("a", app)])
    assert len(res.unscheduled_pods) == 1

    cfg = DEFAULT_CONFIG._replace(f_taints=False)
    res = simulate(cluster, [AppResource("a", app)], sched_config=cfg)
    assert not res.unscheduled_pods


def test_default_config_file_is_identity(tmp_path):
    p = tmp_path / "empty.yaml"
    p.write_text("apiVersion: kubescheduler.config.k8s.io/v1beta1\nkind: KubeSchedulerConfiguration\n")
    assert load_scheduler_config(str(p)) == DEFAULT_CONFIG


def test_extra_plugins_registry():
    """WithExtraRegistry parity: out-of-tree jittable filter and score
    plugins compose into the pipeline."""
    import jax.numpy as jnp

    cluster = ResourceTypes()
    for i in range(3):
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
    app = ResourceTypes()
    app.deployments.append(fx.make_fake_deployment("w", 4, "100m", "128Mi"))

    def ban_node_zero(ec, st, u):
        return jnp.arange(ec.node_valid.shape[0]) != 0

    def prefer_node_two(ec, st, u, feasible):
        return jnp.where(jnp.arange(ec.node_valid.shape[0]) == 2, 100.0, 0.0)

    res = simulate(
        cluster,
        [AppResource("a", app)],
        extra_plugins=(("filter", ban_node_zero), ("score", prefer_node_two, 1000.0)),
    )
    assert not res.unscheduled_pods
    placed = {ns.node.metadata.name: len(ns.pods) for ns in res.node_status}
    assert placed.get("n0", 0) == 0  # custom filter banned it
    assert placed["n2"] == 4  # heavy custom score wins every bind


def test_extra_plugins_validation_and_reason():
    import pytest as _pytest

    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n0", "8", "16Gi"))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("p", "100m", "128Mi"))

    with _pytest.raises(ValueError):
        simulate(cluster, [AppResource("a", app)], extra_plugins=[("filter", lambda *a: None)])
    with _pytest.raises(ValueError):
        simulate(cluster, [AppResource("a", app)], extra_plugins=(("prefilter", lambda *a: None),))
    with _pytest.raises(ValueError):
        simulate(cluster, [AppResource("a", app)], extra_plugins=(("score", lambda *a: None),))

    import jax.numpy as jnp

    def ban_all(ec, st, u):
        return jnp.zeros(ec.node_valid.shape[0], bool)

    res = simulate(cluster, [AppResource("a", app)], extra_plugins=(("filter", ban_all),))
    assert len(res.unscheduled_pods) == 1
    assert "out-of-tree plugin" in res.unscheduled_pods[0].reason


def test_node_prefer_avoid_pods():
    """NodePreferAvoidPods (node_prefer_avoid_pods.go:47-82): an RS-owned
    pod avoids the annotated node when its controller uid matches."""
    import json as _json

    from opensim_tpu.models import expand as _expand

    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("avoided", "8", "16Gi"))
    cluster.nodes.append(fx.make_fake_node("ok", "8", "16Gi"))
    rs = fx.make_fake_replica_set("web", 2, "100m", "128Mi")
    pods = _expand.pods_from_replica_set(rs)
    rs_uid = pods[0].metadata.owner_references[0].uid
    cluster.nodes[0].metadata.annotations["scheduler.alpha.kubernetes.io/preferAvoidPods"] = _json.dumps(
        {"preferAvoidPods": [{"podSignature": {"podController": {"kind": "ReplicaSet", "uid": rs_uid}}}]}
    )
    app = ResourceTypes()
    app.pods.extend(pods)  # pre-expanded pods keep the known controller uid
    res = simulate(cluster, [AppResource("a", app)])
    assert not res.unscheduled_pods
    placed = {ns.node.metadata.name: len(ns.pods) for ns in res.node_status}
    # the 10000-weight avoidance dominates: both replicas land on 'ok'
    assert placed.get("avoided", 0) == 0
    assert placed["ok"] == 2


# ---------------------------------------------------------------------------
# multi-profile + per-plugin args (pkg/simulator/utils.go:304-381 loads the
# full v1beta1 surface; VERDICT r3 #7)
# ---------------------------------------------------------------------------

import pytest

from opensim_tpu.engine.schedconfig import SchedulerProfiles


def _write(tmp_path, text):
    p = tmp_path / "sched.yaml"
    p.write_text(text)
    return str(p)


def test_multi_profile_selects_by_scheduler_name(tmp_path):
    """profiles[0] being a NAMED profile must not shadow default-scheduler:
    pods route by spec.schedulerName, defaulting to default-scheduler."""
    path = _write(tmp_path, """apiVersion: kubescheduler.config.k8s.io/v1beta1
kind: KubeSchedulerConfiguration
profiles:
  - schedulerName: custom-sched
    plugins:
      filter:
        disabled:
          - name: TaintToleration
  - schedulerName: default-scheduler
""")
    cfg = load_scheduler_config(path)
    assert isinstance(cfg, SchedulerProfiles)

    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node(
        "tainted", "8", "16Gi", "110",
        fx.with_taints([{"key": "d", "value": "x", "effect": "NoSchedule"}]),
    ))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("p", "100m", "128Mi"))
    # the pod uses default-scheduler (second profile, defaults) -> taint blocks
    res = simulate(cluster, [AppResource("a", app)], sched_config=cfg)
    assert len(res.unscheduled_pods) == 1
    assert "taint" in res.unscheduled_pods[0].reason

    # a pod explicitly naming custom-sched gets that profile (taints off)
    app2 = ResourceTypes()
    pod = fx.make_fake_pod("p2", "100m", "128Mi")
    pod.spec.scheduler_name = "custom-sched"
    pod.raw.setdefault("spec", {})["schedulerName"] = "custom-sched"
    app2.pods.append(pod)
    res = simulate(cluster, [AppResource("a", app2)], sched_config=cfg)
    assert not res.unscheduled_pods


def test_unknown_profile_pod_gets_explicit_reason(tmp_path):
    path = _write(tmp_path, """kind: KubeSchedulerConfiguration
profiles:
  - schedulerName: default-scheduler
  - schedulerName: batch
""")
    cfg = load_scheduler_config(path)
    assert isinstance(cfg, SchedulerProfiles)
    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n0", "8", "16Gi"))
    app = ResourceTypes()
    pod = fx.make_fake_pod("ghost", "100m", "128Mi")
    pod.spec.scheduler_name = "no-such-scheduler"
    pod.raw.setdefault("spec", {})["schedulerName"] = "no-such-scheduler"
    app.pods.append(pod)
    app.pods.append(fx.make_fake_pod("ok", "100m", "128Mi"))
    res = simulate(cluster, [AppResource("a", app)], sched_config=cfg)
    assert len(res.unscheduled_pods) == 1
    assert "no scheduler profile named 'no-such-scheduler'" in res.unscheduled_pods[0].reason
    placed = sum(len(ns.pods) for ns in res.node_status)
    assert placed == 1  # the default-profile pod scheduled normally


def test_differing_referenced_profiles_schedule_segmented(tmp_path):
    """Differing referenced profiles now schedule via segmentation (round
    5); the capacity-sweep path (resolve_profiles) still fails loudly —
    see test_non_segmentable_interleaving_raises for the segmented path's
    remaining loud failure."""
    from opensim_tpu.engine.schedconfig import resolve_profiles

    path = _write(tmp_path, """kind: KubeSchedulerConfiguration
profiles:
  - schedulerName: default-scheduler
  - schedulerName: lean
    plugins:
      score:
        disabled:
          - name: "*"
""")
    cfg = load_scheduler_config(path)
    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n0", "8", "16Gi"))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("a1", "100m", "128Mi"))
    lean = fx.make_fake_pod("a2", "100m", "128Mi")
    lean.spec.scheduler_name = "lean"
    lean.raw.setdefault("spec", {})["schedulerName"] = "lean"
    app.pods.append(lean)
    res = simulate(cluster, [AppResource("a", app)], sched_config=cfg)
    assert not res.unscheduled_pods
    assert sum(len(ns.pods) for ns in res.node_status) == 2
    assert "segmented multi-profile" in res.engine.skipped["megakernel"]

    # the single-config resolver (scenario sweeps) still refuses
    pods = [p for ns in res.node_status for p in ns.pods]
    with pytest.raises(ValueError, match="differing plugin configurations"):
        resolve_profiles(cfg, pods, ["cpu", "memory"], forced=[False] * len(pods))


def test_fit_ignored_resources(tmp_path):
    """NodeResourcesFitArgs.ignoredResources: a pod over-requesting an
    ignored extended resource schedules anyway (fit skips the column)."""
    path = _write(tmp_path, """kind: KubeSchedulerConfiguration
profiles:
  - schedulerName: default-scheduler
    pluginConfig:
      - name: NodeResourcesFit
        args:
          ignoredResources:
            - example.com/widget
""")
    cfg = load_scheduler_config(path)
    assert isinstance(cfg, SchedulerProfiles)

    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n0", "8", "16Gi"))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod(
        "widgety", "100m", "128Mi",
        fx.with_requests({"example.com/widget": "4"}),
    ))
    # without the config: no node declares the resource -> unschedulable
    res = simulate(cluster, [AppResource("a", app)])
    assert len(res.unscheduled_pods) == 1
    assert "Insufficient example.com/widget" in res.unscheduled_pods[0].reason
    # with ignoredResources: schedules
    res = simulate(cluster, [AppResource("a", app)], sched_config=cfg)
    assert not res.unscheduled_pods


def test_fit_ignored_resource_groups(tmp_path):
    path = _write(tmp_path, """kind: KubeSchedulerConfiguration
profiles:
  - schedulerName: default-scheduler
    pluginConfig:
      - name: NodeResourcesFit
        args:
          ignoredResourceGroups:
            - example.com
""")
    cfg = load_scheduler_config(path)
    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n0", "8", "16Gi"))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod(
        "widgety", "100m", "128Mi",
        fx.with_requests({"example.com/widget": "4"}),
    ))
    res = simulate(cluster, [AppResource("a", app)], sched_config=cfg)
    assert not res.unscheduled_pods


def test_unsupported_fields_fail_loudly(tmp_path):
    # unknown plugin name in an enable list
    with pytest.raises(ValueError, match="unknown plugin 'Fancy'"):
        load_scheduler_config(_write(tmp_path, """kind: KubeSchedulerConfiguration
profiles:
  - plugins:
      score:
        enabled:
          - name: Fancy
"""))
    # percentageOfNodesToScore != 100
    with pytest.raises(ValueError, match="percentageOfNodesToScore=50"):
        load_scheduler_config(_write(tmp_path, """kind: KubeSchedulerConfiguration
percentageOfNodesToScore: 50
profiles:
  - plugins: {}
"""))
    # outcome-changing plugin args
    with pytest.raises(ValueError, match="PodTopologySpread"):
        load_scheduler_config(_write(tmp_path, """kind: KubeSchedulerConfiguration
profiles:
  - pluginConfig:
      - name: PodTopologySpread
        args:
          defaultConstraints:
            - maxSkew: 1
"""))
    # non-default hardPodAffinityWeight
    with pytest.raises(ValueError, match="hardPodAffinityWeight=7"):
        load_scheduler_config(_write(tmp_path, """kind: KubeSchedulerConfiguration
profiles:
  - pluginConfig:
      - name: InterPodAffinity
        args:
          hardPodAffinityWeight: 7
"""))
    # unknown extension point
    with pytest.raises(ValueError, match="extension point 'scorer'"):
        load_scheduler_config(_write(tmp_path, """kind: KubeSchedulerConfiguration
profiles:
  - plugins:
      scorer:
        enabled:
          - name: Simon
"""))
    # duplicate profile names
    with pytest.raises(ValueError, match="duplicate profile"):
        load_scheduler_config(_write(tmp_path, """kind: KubeSchedulerConfiguration
profiles:
  - schedulerName: default-scheduler
  - schedulerName: default-scheduler
"""))


def test_vacuous_plugin_args_accepted(tmp_path):
    """DefaultPreemption / VolumeBinding args cannot change a simulation's
    outcome in either implementation (PARITY.md) and must be accepted."""
    path = _write(tmp_path, """kind: KubeSchedulerConfiguration
profiles:
  - schedulerName: default-scheduler
    pluginConfig:
      - name: DefaultPreemption
        args:
          minCandidateNodesPercentage: 10
      - name: VolumeBinding
        args:
          bindTimeoutSeconds: 600
""")
    cfg = load_scheduler_config(path)
    assert cfg == DEFAULT_CONFIG  # single default profile, no mapped args


# ---------------------------------------------------------------------------
# --tie-break=sample[:seed] (selectHost reservoir sampling,
# generic_scheduler.go:188-210; VERDICT r3 #5)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_tie_break_sample_covers_equal_score_set():
    """Over seeds, sampled placements must cover more than one member of
    the equal-score node set while structural results stay identical to
    the deterministic run — and every sampled bind stays score-optimal."""
    import numpy as np

    from opensim_tpu.engine.scheduler import pad_pod_stream, schedule_pods
    from opensim_tpu.engine.simulator import parse_tie_break, prepare

    assert parse_tie_break("lowest") is None
    assert parse_tie_break("sample") == 0
    assert parse_tie_break("sample:7") == 7
    with pytest.raises(ValueError):
        parse_tie_break("bogus")

    cluster = ResourceTypes()
    for i in range(6):  # identical nodes -> every score ties
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("p", "100m", "128Mi"))
    apps = [AppResource("a", app)]

    det = simulate(cluster, apps, node_pad=8)
    det_node = det.node_status[0].node.metadata.name if det.node_status[0].pods else None
    assert not det.unscheduled_pods

    prep = prepare(cluster, apps, node_pad=8)
    P = len(prep.ordered)
    t, v, f = pad_pod_stream(prep.tmpl_ids, np.ones(P, bool), prep.forced)
    landed = set()
    for seed in range(10):
        out = schedule_pods(
            prep.ec, prep.st0, t, v, f, features=prep.features, tie_seed=seed
        )
        c = int(np.asarray(out.chosen)[0])
        assert c >= 0  # structural parity: still scheduled
        landed.add(c)
    assert len(landed) > 1, "sampling never left the lowest index"

    res = simulate(cluster, apps, node_pad=8, tie_seed=3)
    assert not res.unscheduled_pods
    assert sum(len(ns.pods) for ns in res.node_status) == 1


def test_tie_break_sampled_binds_stay_score_optimal():
    """A sampled run on an affinity-bearing workload must keep every bind
    score-optimal per the independent kube oracle (sampling only permutes
    WITHIN the max set, never off it)."""
    import random as _random

    import numpy as np

    from test_k8s_oracle import _replay_with_scores, random_app, random_cluster

    from opensim_tpu.engine.scheduler import pad_pod_stream, schedule_pods
    from opensim_tpu.engine.simulator import prepare

    rng = _random.Random(29)
    cluster = random_cluster(rng, 8)
    app = random_app(rng, 5)
    prep = prepare(cluster, [AppResource("oracle", app)], node_pad=8)
    P = len(prep.ordered)
    t, v, f = pad_pod_stream(prep.tmpl_ids, np.ones(P, bool), prep.forced)
    out = schedule_pods(
        prep.ec, prep.st0, t, v, f, features=prep.features, tie_seed=11
    )
    chosen = np.asarray(out.chosen)[:P]
    assert _replay_with_scores(prep, cluster, chosen) == 0


def test_forced_pod_scheduler_name_never_routes(tmp_path):
    """A pre-bound (forced) pod bypasses every scheduler — its
    schedulerName must neither raise the differing-profiles error nor mark
    it invalid (review regression)."""
    path = _write(tmp_path, """kind: KubeSchedulerConfiguration
profiles:
  - schedulerName: default-scheduler
  - schedulerName: lean
    plugins:
      score:
        disabled:
          - name: "*"
""")
    cfg = load_scheduler_config(path)
    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n0", "8", "16Gi"))
    bound = fx.make_fake_pod("pre", "100m", "128Mi", fx.with_node_name("n0"))
    bound.raw.setdefault("spec", {})["schedulerName"] = "lean"
    cluster.pods.append(bound)
    app = ResourceTypes()
    app.pods.append(fx.make_fake_pod("new", "100m", "128Mi"))
    res = simulate(cluster, [AppResource("a", app)], sched_config=cfg)
    assert not res.unscheduled_pods
    assert sum(len(ns.pods) for ns in res.node_status) == 2


def test_sweep_auto_masks_unknown_profile_pods(tmp_path):
    """Scenario sweeps must apply the same profile routing as simulate():
    unknown-profile pods are masked out of every scenario so capacity
    verdicts don't chase pods that can never schedule."""
    import numpy as np

    from opensim_tpu.engine.simulator import prepare
    from opensim_tpu.parallel import scenarios

    path = _write(tmp_path, """kind: KubeSchedulerConfiguration
profiles:
  - schedulerName: default-scheduler
  - schedulerName: batch
""")
    cfg = load_scheduler_config(path)
    cluster = ResourceTypes()
    for i in range(3):
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
    app = ResourceTypes()
    ghost = fx.make_fake_pod("ghost", "100m", "128Mi")
    ghost.spec.scheduler_name = "nope"
    ghost.raw.setdefault("spec", {})["schedulerName"] = "nope"
    app.pods.append(ghost)
    app.pods.append(fx.make_fake_pod("ok", "100m", "128Mi"))
    prep = prepare(cluster, [AppResource("a", app)], node_pad=8)
    P = len(prep.ordered)
    N = prep.ec.node_valid.shape[0]
    node_valid = np.zeros((2, N), bool)
    node_valid[:, :3] = True
    res = scenarios.sweep_auto(prep, node_valid, np.ones((2, P), bool), config=cfg)
    # the ghost pod is masked (not counted unscheduled), the ok pod binds
    assert list(np.asarray(res.unscheduled)) == [0, 0]
    ghost_idx = [i for i, p in enumerate(prep.ordered)
                 if p.metadata.name == "ghost"][0]
    assert (np.asarray(res.chosen)[:, ghost_idx] == -1).all()


# ---------------------------------------------------------------------------
# segmented multi-profile scheduling (VERDICT r4 #7; utils.go:304-381)
# ---------------------------------------------------------------------------


def _two_profile_config(tmp_path):
    p = tmp_path / "profiles.yaml"
    p.write_text(
        "apiVersion: kubescheduler.config.k8s.io/v1beta1\n"
        "kind: KubeSchedulerConfiguration\n"
        "profiles:\n"
        "  - schedulerName: default-scheduler\n"
        "  - schedulerName: packer\n"
        "    plugins:\n"
        "      score:\n"
        "        disabled:\n"
        "          - name: NodeResourcesBalancedAllocation\n"
        "          - name: NodeResourcesLeastAllocated\n"
    )
    return load_scheduler_config(str(p))


def test_segmented_two_differing_profiles_schedule(tmp_path):
    """Two differing profiles in one stream: consecutive scans share the
    carry; each segment runs its own plugin config (the packer profile
    packs where the default spreads)."""
    cfg = _two_profile_config(tmp_path)
    cluster = ResourceTypes()
    for i in range(4):
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
    rt = ResourceTypes()
    d1 = fx.make_fake_deployment("default-app", 6, "500m", "1Gi")
    d2 = fx.make_fake_deployment("packer-app", 6, "500m", "1Gi")
    d2.template_spec.scheduler_name = "packer"
    rt.deployments.extend([d1, d2])
    res = simulate(cluster, [AppResource("a", rt)], sched_config=cfg)
    assert not res.unscheduled_pods
    assert res.engine.name in ("native", "xla")
    assert "segmented multi-profile" in res.engine.skipped["megakernel"]
    by_app = {}
    for ns in res.node_status:
        for p in ns.pods:
            app = p.metadata.labels.get("app", "")
            by_app.setdefault(app, {}).setdefault(ns.node.metadata.name, 0)
            by_app[app][ns.node.metadata.name] += 1
    # default profile spreads its 6 pods; the packer profile concentrates
    assert len(by_app["default-app"]) == 4
    assert max(by_app["packer-app"].values()) >= 4


def test_segmented_profiles_share_the_carry(tmp_path):
    """Segment 2 must see segment 1's binds: a full node cannot be reused,
    and a failing pod's reason reflects the shared usage."""
    cfg = _two_profile_config(tmp_path)
    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n0", "8", "16Gi"))
    cluster.nodes.append(fx.make_fake_node("n1", "8", "16Gi"))
    rt = ResourceTypes()
    d1 = fx.make_fake_deployment("filler", 2, "7", "1Gi")  # one per node
    d2 = fx.make_fake_deployment("late", 2, "4", "1Gi")
    d2.template_spec.scheduler_name = "packer"
    rt.deployments.extend([d1, d2])
    res = simulate(cluster, [AppResource("a", rt)], sched_config=cfg)
    # both nodes carry one 7-cpu filler; neither fits a 4-cpu late pod
    assert len(res.unscheduled_pods) == 2
    for up in res.unscheduled_pods:
        assert "0/2 nodes are available: 2 Insufficient cpu." == up.reason


def test_segmented_unknown_profile_reason(tmp_path):
    cfg = _two_profile_config(tmp_path)
    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n0", "8", "16Gi"))
    rt = ResourceTypes()
    d1 = fx.make_fake_deployment("ok", 1, "500m", "1Gi")
    d2 = fx.make_fake_deployment("ghost", 1, "500m", "1Gi")
    d2.template_spec.scheduler_name = "packer"
    d3 = fx.make_fake_deployment("lost", 1, "500m", "1Gi")
    d3.template_spec.scheduler_name = "no-such-profile"
    rt.deployments.extend([d1, d2, d3])
    res = simulate(cluster, [AppResource("a", rt)], sched_config=cfg)
    assert len(res.unscheduled_pods) == 1
    assert "no scheduler profile named 'no-such-profile'" in res.unscheduled_pods[0].reason


def test_non_segmentable_interleaving_raises(tmp_path):
    """A pathological alternation (one scan per pod) still fails loudly."""
    from opensim_tpu.engine.schedconfig import MAX_PROFILE_SEGMENTS

    cfg = _two_profile_config(tmp_path)
    cluster = ResourceTypes()
    cluster.nodes.append(fx.make_fake_node("n0", "64", "64Gi"))
    rt = ResourceTypes()
    for i in range(MAX_PROFILE_SEGMENTS + 2):
        pod = fx.make_fake_pod(f"p{i}", "10m", "16Mi")
        if i % 2:
            pod.spec.scheduler_name = "packer"
        rt.pods.append(pod)
    with pytest.raises(ValueError, match="non-segmentable"):
        simulate(cluster, [AppResource("a", rt)], sched_config=cfg)


def test_differing_profiles_capacity_sweep(tmp_path):
    """Full `simon apply` with DIFFERING profiles and a cluster that needs
    new nodes: the batched sweep cannot run one pipeline, so the planner
    probes candidate counts with segmented masked simulations and still
    finds the minimum node count."""
    import yaml as _yaml

    from opensim_tpu.planner.apply import Applier, Options

    cfgdir = tmp_path / "cluster"
    cfgdir.mkdir()
    (cfgdir / "node.yaml").write_text(
        _yaml.safe_dump(fx.make_fake_node("n0", "8", "16Gi").raw)
    )
    newnode = tmp_path / "newnode"
    newnode.mkdir()
    (newnode / "node.yaml").write_text(
        _yaml.safe_dump(fx.make_fake_node("tmpl", "16", "32Gi").raw)
    )
    appdir = tmp_path / "app"
    appdir.mkdir()
    d1 = fx.make_fake_deployment("default-app", 6, "2", "2Gi")
    d2 = fx.make_fake_deployment("packer-app", 6, "2", "2Gi")
    d2.template_spec.scheduler_name = "packer"
    d2.raw["spec"]["template"].setdefault("spec", {})["schedulerName"] = "packer"
    (appdir / "apps.yaml").write_text(
        "---\n".join(_yaml.safe_dump(w.raw) for w in (d1, d2))
    )
    sched = tmp_path / "profiles.yaml"
    sched.write_text(
        "apiVersion: kubescheduler.config.k8s.io/v1beta1\n"
        "kind: KubeSchedulerConfiguration\n"
        "profiles:\n"
        "  - schedulerName: default-scheduler\n"
        "  - schedulerName: packer\n"
        "    plugins:\n"
        "      score:\n"
        "        disabled:\n"
        "          - name: NodeResourcesBalancedAllocation\n"
        "          - name: NodeResourcesLeastAllocated\n"
    )
    cfg = tmp_path / "simon-config.yaml"
    cfg.write_text(
        "apiVersion: simon/v1alpha1\nkind: Config\nmetadata: {name: t}\n"
        "spec:\n"
        f"  cluster: {{customConfig: '{cfgdir}'}}\n"
        f"  newNode: '{newnode}'\n"
        "  appList:\n"
        f"    - {{name: apps, path: '{appdir}'}}\n"
    )
    out = tmp_path / "report.txt"
    rc = Applier(
        Options(
            simon_config=str(cfg),
            default_scheduler_config=str(sched),
            output_file=str(out),
            max_new_nodes=8,
        )
    ).run()
    text = out.read_text()
    assert rc == 0, text
    assert "Simulation success!" in text
    # 12 pods x 2 cpu = 24 cpu; n0 has 8 => at least 1 new 16-cpu node
    assert "(added" in text
    assert "segmented multi-profile" in text  # engine footer names the path


def test_sweep_auto_mixed_profiles_matches_solo_segmented(tmp_path):
    """The ISSUE 8 satellite: DIFFERING profiles no longer raise in a
    scenario sweep — they route through per-segment scans sharing each
    scenario's carry, and every scenario's placements equal a solo
    segmented simulate of that sub-cluster."""
    import numpy as np

    from opensim_tpu.engine.simulator import (
        prepare, restore_bind_state, snapshot_bind_state,
    )
    from opensim_tpu.parallel import scenarios

    cfg = _two_profile_config(tmp_path)
    cluster = ResourceTypes()
    for i in range(6):
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
    rt = ResourceTypes()
    d1 = fx.make_fake_deployment("default-app", 5, "500m", "1Gi")
    d2 = fx.make_fake_deployment("packer-app", 5, "500m", "1Gi")
    d2.template_spec.scheduler_name = "packer"
    rt.deployments.extend([d1, d2])
    prep = prepare(cluster, [AppResource("a", rt)], node_pad=8)
    P = len(prep.ordered)
    N = int(np.asarray(prep.ec_np.node_valid).shape[0])
    ks = (3, 4, 6)
    node_valid = np.zeros((len(ks), N), bool)
    for s, k in enumerate(ks):
        node_valid[s, :k] = True
    res = scenarios.sweep_auto(prep, node_valid, np.ones((len(ks), P), bool), config=cfg)

    snap = snapshot_bind_state(prep)
    for s, k in enumerate(ks):
        sub = ResourceTypes(nodes=cluster.nodes[:k])
        solo = simulate(sub, [], prep=prep, node_valid=node_valid[s], sched_config=cfg)
        restore_bind_state(prep, snap)
        ch = np.asarray(res.chosen)[s]
        assert len(solo.unscheduled_pods) == int(np.asarray(res.unscheduled)[s])
        placed = {
            f"{p.metadata.namespace}/{p.metadata.name}": ns.node.metadata.name
            for ns in solo.node_status
            for p in ns.pods
        }
        for i, pod in enumerate(prep.ordered):
            key = f"{pod.metadata.namespace}/{pod.metadata.name}"
            got = prep.meta.node_names[ch[i]] if ch[i] >= 0 else None
            assert placed.get(key) == got, (s, key)


def test_sweep_auto_single_profile_still_routes_one_config(tmp_path):
    """A multi-profile config whose referenced profiles RESOLVE identically
    keeps the single-config sweep path (no segmented scans)."""
    import numpy as np

    from opensim_tpu.engine.simulator import prepare
    from opensim_tpu.parallel import scenarios

    cfg = _two_profile_config(tmp_path)
    cluster = ResourceTypes()
    for i in range(4):
        cluster.nodes.append(fx.make_fake_node(f"n{i}", "8", "16Gi"))
    rt = ResourceTypes()
    rt.deployments.append(fx.make_fake_deployment("only-default", 4, "500m", "1Gi"))
    prep = prepare(cluster, [AppResource("a", rt)], node_pad=8)
    P = len(prep.ordered)
    N = int(np.asarray(prep.ec_np.node_valid).shape[0])
    node_valid = np.zeros((2, N), bool)
    node_valid[:, :4] = True
    res = scenarios.sweep_auto(prep, node_valid, np.ones((2, P), bool), config=cfg)
    assert list(np.asarray(res.unscheduled)) == [0, 0]
